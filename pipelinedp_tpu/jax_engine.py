"""The fused TPU aggregation path.

This is the performance core of the framework: the entire
``DPEngine.aggregate`` dataflow (reference call stack §3.1 of SURVEY.md —
extract → bound contributions → combine per key → select partitions →
noise) compiled into ONE XLA program over integer-encoded arrays:

    host:   extract + integer-encode (pid, pk, value); calibrate noise
    device: lexsort by (pid, pk, rand)            [shuffle 1+2 fused]
            → segment boundaries per (pid, pk)
            → linf bound  = rank-in-segment < max_contributions_per_partition
            → per-segment accumulators (segment_sum)    [create_accumulator]
            → L0 bound    = random rank of segment within pid < l0
            → per-pk accumulators (segment_sum)         [merge/combine]
            → batched partition selection over the pk axis
            → batched percentile tree walk (when requested)
    host:   float64 scalar release via the shared dp_computations
            mechanisms (float32 device noise would quantize to a large
            aggregate's ULP grid); decode pk vocabulary, wrap
            MetricsTuple rows

Two-phase budget protocol: noise scales, selection tables/thresholds and
the PRNG key are *runtime inputs* to the compiled function — budgets are
computed after graph construction and never trigger recompilation. Shapes
are padded to powers of two so repeated runs with similar sizes reuse the
compile cache.

Supported in the fused plane: COUNT, PRIVACY_ID_COUNT, SUM (both clipping
modes), MEAN, VARIANCE, VECTOR_SUM, PERCENTILE, public and private
partitions, ``contribution_bounds_already_enforced``.

PERCENTILE never materializes dense per-partition trees (height-4 ×
branching-16 = 69,904 nodes per partition would be O(P·nodes) HBM): the
quantile walk runs level-by-level over ALL partitions at once, counting
each level's child buckets with one segment_sum over the rows, and node
noise is a pure function of (partition, node index) — one batched
counter-based threefry draw per level (``ops/counter_rng.py``), the
stateless equivalent of the host tree's noisy-count memoization
(reference ``pipeline_dp/combiners.py:402-476``; host twin
``ops/quantile_tree.py``). When the bottom walk's [P, Q, span] subtree
block exceeds ``_SUBHIST_BYTE_CAP``, the partition axis chunks into
blocks walked one at a time — bit-identical to the unchunked walk,
because the noise is keyed by GLOBAL (partition, node).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pipelinedp_tpu import dp_computations
from pipelinedp_tpu.aggregate_params import (AggregateParams, NoiseKind,
                                             NormKind,
                                             PartitionSelectionStrategy)
from pipelinedp_tpu.combiners import _create_named_tuple_instance
from pipelinedp_tpu.obs.costs import instrumented_jit
from pipelinedp_tpu.ops import partition_selection as ps_ops
from pipelinedp_tpu.ops import quantile_tree as quantile_tree_ops
from pipelinedp_tpu.ops import segment as seg_ops


def _pad_rows(n: int) -> int:
    """Row-axis padding: the next multiple of 8192 (a whole number of
    (8, 128) f32 tiles, and one shared compile shape for small tests).
    Rows used to pad to a power of two, which wastes up to 2x of every
    row-space op (sort, scatters, elementwise) — a 10M-row pipeline ran
    all its row passes at 2^24 = 16.8M rows; measured on v5e, sorts and
    scatters at a non-power-of-two length run at full speed, so the
    tight padding is a ~1.4-1.7x cut of the whole row plane. The
    partition axis keeps power-of-two padding (``_pad_pow2``): selection
    bit-parity on meshes relies on it."""
    return max(8192, -(-n // 8192) * 8192)


def _pad_pow2(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << (n - 1).bit_length())


@dataclasses.dataclass(frozen=True)
class FusedConfig:
    """Static (compile-time) configuration derived from AggregateParams."""
    metrics: Tuple[str, ...]  # subset of the fused metric names, in order
    noise_kind: NoiseKind
    linf: Optional[int]
    l0: int
    per_partition_bounds: bool  # SUM clips the per-(pid,pk) sum, not rows
    min_value: Optional[float]
    max_value: Optional[float]
    min_sum_per_partition: Optional[float]
    max_sum_per_partition: Optional[float]
    vector_size: Optional[int]
    vector_norm_kind: Optional[NormKind]
    vector_max_norm: Optional[float]
    selection: Optional[PartitionSelectionStrategy]  # None = public
    bounds_already_enforced: bool
    percentiles: Tuple[float, ...] = ()  # PERCENTILE(p) parameters, in order
    # Total-cap bounding: M rows per privacy unit across ALL partitions
    # (l0/linf are None in this mode).
    max_contributions: Optional[int] = None
    # VECTOR_SUM accumulator discipline: "f32" (plain float32
    # segment_sum — the historical path) or "fx" (24-bit fixed-point
    # coordinate lanes, exact). Resolved from the vector_accumulator
    # knob in from_params; a FusedConfig built elsewhere keeps the
    # historical default. Riding on the config (already a static jit
    # argument on every hot path) means a knob flip re-traces.
    vector_accumulator: str = "f32"
    # Pinned D tile for the wide-D vector segment-sum kernel (the
    # segsum_wide_d_block knob; 0 = the envelope's choice).
    wide_d_block: int = 0

    @property
    def selection_l0(self) -> int:
        """L0 for partition selection: a unit touches at most this many
        partitions in either bounding mode."""
        return (self.max_contributions if self.max_contributions is not None
                else self.l0)

    @property
    def needs_values(self) -> bool:
        """Whether any requested metric reads the value column (kept next
        to FUSABLE_METRICS so new metrics update both in one place)."""
        return bool(set(self.metrics) & _VALUE_METRICS
                    ) or self.per_partition_bounds

    @staticmethod
    def from_params(params: AggregateParams,
                    public: bool) -> "FusedConfig":
        names = []
        percentiles = []
        for m in params.metrics:
            if m.is_percentile:
                percentiles.append(float(m.parameter))
                if "PERCENTILE" not in names:
                    names.append("PERCENTILE")
            else:
                names.append(m.name)
        vector_accumulator = "f32"
        wide_d_block = 0
        if params.vector_size:
            from pipelinedp_tpu import plan as plan_mod
            vector_accumulator = str(
                plan_mod.knob_value("vector_accumulator"))
            wide_d_block = int(
                plan_mod.knob_value("segsum_wide_d_block"))
        return FusedConfig(
            metrics=tuple(names),
            percentiles=tuple(percentiles),
            noise_kind=params.noise_kind,
            linf=params.max_contributions_per_partition,
            l0=params.max_partitions_contributed,
            max_contributions=params.max_contributions,
            per_partition_bounds=params.bounds_per_partition_are_set,
            min_value=params.min_value,
            max_value=params.max_value,
            min_sum_per_partition=params.min_sum_per_partition,
            max_sum_per_partition=params.max_sum_per_partition,
            vector_size=params.vector_size,
            vector_norm_kind=params.vector_norm_kind,
            vector_max_norm=params.vector_max_norm,
            selection=(None if public else
                       params.partition_selection_strategy),
            bounds_already_enforced=(
                params.contribution_bounds_already_enforced),
            vector_accumulator=vector_accumulator,
            wide_d_block=wide_d_block,
        )


FUSABLE_METRICS = {"COUNT", "PRIVACY_ID_COUNT", "SUM", "MEAN", "VARIANCE",
                   "VECTOR_SUM", "PERCENTILE"}
# The fused metrics that read the value column (the rest only count rows
# or segments, so their kernels run on an all-zeros values array).
_VALUE_METRICS = {"SUM", "MEAN", "VARIANCE", "VECTOR_SUM", "PERCENTILE"}


def params_are_fusable(params: AggregateParams) -> bool:
    if params.custom_combiners:
        return False
    # (Total-cap ``max_contributions`` bounding is fused too, including
    # PERCENTILE: the engine rejects only VECTOR_SUM with it, and in
    # bounds-already-enforced mode no bounding runs anywhere.)
    for m in params.metrics:
        if m.is_percentile:
            # The quantile walk needs real tree bounds. min_value may be
            # None here (sum-per-partition bounds mode); a zero-width
            # range never arrives (AggregateParams rejects it for
            # percentiles at construction). A pathologically tiny (but
            # valid) range falls through to the generic host path: the
            # fused leaf arithmetic folds n_leaves/range into ONE f32
            # constant (see ``_qrows`` for why), which overflows for
            # range < ~1.9e-34 — the host tree computes in f64 and
            # handles those ranges fine.
            if (params.min_value is None or
                    not params.min_value < params.max_value):
                return False
            n_leaves = (quantile_tree_ops.DEFAULT_BRANCHING_FACTOR **
                        quantile_tree_ops.DEFAULT_TREE_HEIGHT)
            inv = n_leaves / (float(params.max_value) -
                              float(params.min_value))
            if inv > float(np.finfo(np.float32).max):
                return False
        elif m.name not in FUSABLE_METRICS:
            return False
    return True


# ---------------------------------------------------------------------------
# Host-side encoding
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ArrayDataset:
    """Columnar input: the zero-copy fast path into the fused plane.

    When the caller already has NumPy columns (a Parquet/CSV load, a
    feature pipeline), passing them as an ArrayDataset skips the
    per-row Python extractor loop entirely — encoding becomes a
    vectorized ``np.unique``. ``values`` may be [N] scalars or [N, D]
    vectors. ``DataExtractors`` are not needed (pass an empty one).

    Aggregating the same dataset repeatedly (multiple metrics, parameter
    tuning, utility-analysis sweeps) reuses the integer-encoded columns
    AND their on-device placement: the slow host<->device link is paid
    once, not per aggregation. The columns are therefore treated as
    immutable once the first aggregation runs — call
    ``invalidate_cache()`` after mutating them in place.
    """
    privacy_ids: Optional[np.ndarray]
    partition_keys: np.ndarray
    values: Optional[np.ndarray] = None

    def __len__(self):
        return len(self.partition_keys)

    def invalidate_cache(self) -> None:
        """Drops cached encodings/device buffers (after in-place edits)."""
        self.__dict__.pop("_encode_cache", None)

    def _cached_encode(self, key, build):
        cache = self.__dict__.setdefault("_encode_cache", {})
        if key not in cache:
            cache[key] = build()
        return cache[key]

    def to_rows(self):
        """Row-tuple view for the generic (non-fused) backends."""
        n = len(self.partition_keys)
        pids = (self.privacy_ids if self.privacy_ids is not None else
                np.zeros(n, np.int64))
        vals = (self.values if self.values is not None else
                np.zeros(n, np.float64))
        return list(zip(pids.tolist(), self.partition_keys.tolist(),
                        vals.tolist()))


@dataclasses.dataclass
class EncodedData:
    """Integer-encoded rows + the pk vocabulary for decoding."""
    pid: np.ndarray  # int32 [N]
    pk: np.ndarray  # int32 [N]
    values: np.ndarray  # f32 [N] or [N, D]
    pk_vocab: List[Any]  # dense pk index -> original key
    n_rows: int


def _int_factorize(arr: np.ndarray):
    """Sort-free factorization for integer keys with a manageable range:
    O(n + range) via a presence table instead of np.unique's O(n log n)
    sort. Returns (uniq values ascending, int32 inverse) or None when the
    range is too wide to be worth a table."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return None
    mn = int(arr.min())
    mx = int(arr.max())
    span = mx - mn + 1
    if span > max(4 * arr.size, 1 << 22):
        return None
    # Offsets must not be computed in a dtype that can overflow: narrow
    # signed dtypes wrap on `arr - mn`, and uint64 values above int64 max
    # wrap on the cast. Unsigned subtraction is exact (arr >= mn), signed
    # fits int64 by construction.
    if arr.dtype.kind == "u":
        offs = (arr - np.asarray(mn, arr.dtype)).astype(np.int64)
    else:
        offs = arr.astype(np.int64) - mn
    present = np.zeros(span, dtype=bool)
    present[offs] = True
    uniq_off = np.flatnonzero(present)
    lookup = np.empty(span, dtype=np.int32)
    lookup[uniq_off] = np.arange(len(uniq_off), dtype=np.int32)
    uniq = uniq_off.astype(arr.dtype) + np.asarray(mn, arr.dtype)
    return uniq, lookup[offs]


def _unique_inverse(arr: np.ndarray):
    """``np.unique(arr, return_inverse=True)`` with the native hash
    factorizer (``native/encode.cc``: O(N + U log U) vs the full O(N log N)
    sort) when the toolchain can build it; inverse always int32."""
    if arr.dtype.kind in "iu" and arr.dtype.itemsize <= 8:
        try:
            from pipelinedp_tpu import native
            if native.encode_available():
                # factorize_i64 itself rejects uint64 values that would
                # wrap; that ValueError lands in the fallback below.
                uniq, inv = native.factorize_i64(arr)
                return uniq.astype(arr.dtype), inv
        except Exception:  # never let the fast path break ingest
            pass
    uniq, inv = np.unique(arr, return_inverse=True)
    return uniq, inv.astype(np.int32)


def _pid_ids(pid_arr: np.ndarray) -> np.ndarray:
    """int32 ids for privacy units: any injective mapping works (the kernel
    only groups by equality), so in-range integer ids pass through without
    the np.unique sort. PAD_ID (int32 max) is reserved for padding rows."""
    if (pid_arr.dtype.kind in "iu" and pid_arr.size and
            pid_arr.min() >= 0 and pid_arr.max() < np.iinfo(np.int32).max):
        return pid_arr.astype(np.int32)
    fac = _int_factorize(pid_arr)
    if fac is not None:
        return fac[1]
    return _unique_inverse(pid_arr)[1]


def array_dataset_to_rows(ds: ArrayDataset, data_extractors,
                          require_pid: bool = True):
    """Columnar input on a generic (row-based) backend: expand to row
    tuples with positional extractors — shared by ``DPEngine._aggregate``
    and the histogram graph. Caller-supplied extractors are honored when
    a partition extractor is set."""
    import operator

    from pipelinedp_tpu.dp_engine import DataExtractors

    if ds.privacy_ids is None and require_pid:
        raise ValueError(
            "ArrayDataset.privacy_ids must be set unless "
            "contribution_bounds_already_enforced is True.")
    rows = ds.to_rows()
    if data_extractors.partition_extractor is None:
        data_extractors = DataExtractors(
            privacy_id_extractor=(None if not require_pid else
                                  operator.itemgetter(0)),
            partition_extractor=operator.itemgetter(1),
            value_extractor=operator.itemgetter(2))
    return rows, data_extractors


def pad_and_put(encoded: EncodedData, vector_size: Optional[int],
                with_values: bool = True):
    """One batched h2d transfer of the exact-size encoded columns; padding
    happens on device and the padding mask is derived from a scalar — the
    (slow, high-latency) host link moves only real rows in a single round
    trip. Id columns ship at their minimal byte width (the link runs at
    tens of MB/s, so bytes ARE wall time): uint16 when the ids fit,
    3xuint8 planes for ids in [2^16, 2^24) — dense-factorized vocabularies
    routinely land there — widened back to int32 on device.
    ``with_values=False`` skips the value column entirely (COUNT-style
    aggregations never read it). Returns (pid, pk, values, valid) padded
    to a power of two.

    The placed arrays are cached on the EncodedData: repeated
    aggregations of the same dataset (tuning sweeps, multi-metric
    pipelines) pay the tunnel transfer once. Id columns and the value
    column cache INDEPENDENTLY — a COUNT pass followed by a SUM pass
    ships the ids once and then only adds the value transfer (still one
    batched device_put per call for whatever is missing)."""
    n = encoded.n_rows
    n_pad = _pad_rows(n)
    cache = encoded.__dict__.setdefault("_device_cache", {})
    vals_key = ("values", vector_size)
    need_ids = "ids" not in cache
    need_vals = with_values and vals_key not in cache

    if need_ids or need_vals:
        host = []
        pid_planes = pk_planes = ()
        if need_ids:
            pid_planes = _narrow_ids(encoded.pid)
            pk_planes = _narrow_ids(encoded.pk)
            host += list(pid_planes) + list(pk_planes)
        if need_vals:
            host.append(encoded.values)
        dev = jax.device_put(tuple(host))
        if need_ids:
            n_pid = len(pid_planes)
            pid = jnp.zeros(n_pad, jnp.int32).at[:n].set(
                _widen_ids(dev[:n_pid]))
            pk = jnp.zeros(n_pad, jnp.int32).at[:n].set(
                _widen_ids(dev[n_pid:n_pid + len(pk_planes)]))
            valid = jnp.arange(n_pad) < n
            cache["ids"] = (pid, pk, valid)
        if need_vals:
            shape = (n_pad, vector_size) if vector_size else (n_pad,)
            cache[vals_key] = jnp.zeros(shape, jnp.float32).at[:n].set(
                dev[-1])

    pid, pk, valid = cache["ids"]
    if with_values:
        values = cache[vals_key]
    else:
        zeros_key = ("zeros", vector_size)
        if zeros_key not in cache:
            shape = (n_pad, vector_size) if vector_size else (n_pad,)
            cache[zeros_key] = jnp.zeros(shape, jnp.float32)
        values = cache[zeros_key]
    return pid, pk, values, valid


def _plane_spec(max_id: int) -> str:
    """Byte-width tier for an id column: one policy for the single-batch
    and streaming ship paths (streaming decides ONCE from the global max
    so every batch shares a jit signature)."""
    if max_id < (1 << 16):
        return "u16"
    if max_id < (1 << 24):
        return "u8x3"
    return "i32"


def _narrow_ids(arr, spec: Optional[str] = None):
    """Minimal-byte-width host planes of a non-negative id column
    (encode() guarantees non-negative ids). ``spec`` forces a tier
    decided elsewhere (streaming's global-max decision)."""
    if spec is None:
        spec = _plane_spec(int(arr.max()) if arr.size else 0)
    if spec == "u16":
        return (arr.astype(np.uint16),)
    if spec == "u8x3":
        a32 = arr.astype(np.uint32)
        return (a32.astype(np.uint8), (a32 >> 8).astype(np.uint8),
                (a32 >> 16).astype(np.uint8))
    return (arr,)


def _widen_ids(planes) -> jnp.ndarray:
    if len(planes) == 1:
        return planes[0].astype(jnp.int32)
    b0, b1, b2 = (p.astype(jnp.int32) for p in planes)
    return b0 | (b1 << 8) | (b2 << 16)


def _encode_arrays(ds: ArrayDataset, vector_size: Optional[int],
                   public_partitions: Optional[Sequence],
                   require_pid: bool = True) -> EncodedData:
    """Vectorized encode of columnar input (no per-row Python)."""
    pk_arr = np.asarray(ds.partition_keys)
    n = pk_arr.shape[0]
    if ds.privacy_ids is None and require_pid:
        raise ValueError(
            "ArrayDataset.privacy_ids must be set unless "
            "contribution_bounds_already_enforced is True — without them "
            "all rows would be attributed to one privacy unit and almost "
            "all data silently dropped by contribution bounding.")
    pid_arr = (np.asarray(ds.privacy_ids) if ds.privacy_ids is not None
               else np.zeros(n, np.int64))
    values = (np.asarray(ds.values, dtype=np.float32)
              if ds.values is not None else np.zeros(n, np.float32))
    if public_partitions is not None:
        vocab = np.asarray(list(public_partitions))
        sorter = np.argsort(vocab, kind="stable")
        pos = np.searchsorted(vocab, pk_arr, sorter=sorter)
        pos = np.clip(pos, 0, len(vocab) - 1)
        candidate = sorter[pos]
        mask = vocab[candidate] == pk_arr
        pk_idx = candidate[mask].astype(np.int32)
        pid_arr = pid_arr[mask]
        values = values[mask]
        pk_vocab = list(vocab.tolist())
    else:
        fac = _int_factorize(pk_arr)
        if fac is not None:
            uniq, pk_idx = fac
        else:
            uniq, pk_idx = _unique_inverse(pk_arr)
        pk_vocab = list(uniq.tolist())
    pid_idx = _pid_ids(pid_arr)
    if vector_size:
        values = values.reshape(len(values), vector_size)
    return EncodedData(pid=pid_idx, pk=pk_idx,
                       values=values, pk_vocab=pk_vocab,
                       n_rows=len(pk_idx))


def _itemgetter_index(fn) -> Optional[int]:
    """The index a plain single-item ``operator.itemgetter`` selects, or
    None for any other callable. Resolved by probing with a recording
    object — exact-type-gated, so only true positional selectors (which
    can do nothing but index) qualify."""
    import operator
    if type(fn) is not operator.itemgetter:
        return None

    class _Probe:
        def __init__(self):
            self.indices = []

        def __getitem__(self, i):
            self.indices.append(i)
            return i

    probe = _Probe()
    try:
        result = fn(probe)
    except Exception:
        return None
    if len(probe.indices) == 1 and result == probe.indices[0]:
        return probe.indices[0]
    return None


def _rows_to_arrays(rows, data_extractors,
                    require_pid: bool) -> Optional[ArrayDataset]:
    """The vectorized extractor bridge: when every extractor is a plain
    ``operator.itemgetter`` over tuple rows, ingest transposes the rows
    once at C level (``zip(*rows)``) instead of paying three Python
    extractor calls per row, and the columns take the same vectorized
    encode as an ArrayDataset. Returns None when the rows/extractors
    don't qualify (arbitrary callables fall back to the row loop)."""
    if not isinstance(rows, (list, tuple)) or not rows:
        return None
    if not isinstance(rows[0], (tuple, list)):
        return None
    i_pid = _itemgetter_index(data_extractors.privacy_id_extractor)
    i_pk = _itemgetter_index(data_extractors.partition_extractor)
    i_val = _itemgetter_index(data_extractors.value_extractor)
    if i_pk is None:
        return None
    if require_pid and i_pid is None:
        return None
    if (data_extractors.value_extractor is not None and i_val is None):
        return None
    # Per-column extraction: a plain `[r[i] for r in rows]` comprehension
    # benches ~3.5x faster than both np.asarray(rows) and zip(*rows) for
    # multi-million-row lists (one bytecode-level loop per column, no
    # intermediate 2-D object array). Dtypes are probed on a small prefix
    # first so unsupported columns (string keys) bail out without paying
    # a full O(n) pass before the row-loop fallback.
    def col(i, probe=0):
        if i is None:
            return None
        try:
            sample = rows[:256] if probe else rows
            arr = np.asarray([r[i] for r in sample])
        except (IndexError, ValueError, TypeError):
            return None
        return None if arr.dtype == object else arr

    # Id columns must be numeric: np.unique on large string columns is
    # slower than the dict-based row loop, so strings keep that path.
    def usable(i, need_1d):
        a = col(i, probe=1)
        return (a is not None and a.dtype.kind in "iuf" and
                (not need_1d or a.ndim == 1))

    if not usable(i_pk, True):
        return None
    if i_pid is not None and not usable(i_pid, True):
        return None
    if i_val is not None and not usable(i_val, False):
        return None

    def full(i, need_1d):
        a = col(i)
        if (a is None or a.dtype.kind not in "iuf" or
                (need_1d and a.ndim != 1)):
            return None
        return a

    pk_arr = full(i_pk, True)
    if pk_arr is None:
        return None
    pid_arr = None
    if i_pid is not None:
        pid_arr = full(i_pid, True)
        if pid_arr is None:
            return None
    val_arr = None
    if i_val is not None:
        val_arr = full(i_val, False)
        if val_arr is None:
            return None
    return ArrayDataset(privacy_ids=pid_arr, partition_keys=pk_arr,
                        values=val_arr)


def encode(rows, data_extractors, vector_size: Optional[int],
           public_partitions: Optional[Sequence] = None,
           require_pid: bool = True) -> EncodedData:
    """Extract + integer-encode on host. With public partitions the pk
    vocabulary IS the public list — non-public rows are dropped and missing
    public partitions appear as all-zero accumulator rows for free."""
    if isinstance(rows, ArrayDataset):
        if public_partitions is None:
            # Cacheable: the encode is a pure function of the columns.
            # (Public-partition encodes depend on the passed list and are
            # not cached — the list has no cheap identity.)
            return rows._cached_encode(
                ("encode", vector_size, require_pid),
                lambda: _encode_arrays(rows, vector_size, None, require_pid))
        return _encode_arrays(rows, vector_size, public_partitions,
                              require_pid)
    bridged = _rows_to_arrays(rows, data_extractors, require_pid)
    if bridged is not None:
        return _encode_arrays(bridged, vector_size, public_partitions,
                              require_pid)
    pids, pks, vals = [], [], []
    pid_ex = data_extractors.privacy_id_extractor
    pk_ex = data_extractors.partition_extractor
    val_ex = data_extractors.value_extractor
    if pid_ex is None and require_pid:
        raise ValueError(
            "privacy_id_extractor must be set unless "
            "contribution_bounds_already_enforced is True.")
    for row in rows:
        pids.append(pid_ex(row) if pid_ex else 0)
        pks.append(pk_ex(row))
        vals.append(val_ex(row) if val_ex else 0.0)

    if public_partitions is not None:
        pk_vocab = list(public_partitions)
        pk_index = {k: i for i, k in enumerate(pk_vocab)}
        keep = [i for i, k in enumerate(pks) if k in pk_index]
        pids = [pids[i] for i in keep]
        vals = [vals[i] for i in keep]
        pk_idx = np.fromiter((pk_index[pks[i]] for i in keep),
                             dtype=np.int32, count=len(keep))
    else:
        uniq = sorted(set(pks), key=repr)
        pk_index = {k: i for i, k in enumerate(uniq)}
        pk_vocab = uniq
        pk_idx = np.fromiter((pk_index[k] for k in pks), dtype=np.int32,
                             count=len(pks))

    uniq_pids = {p: i for i, p in enumerate(dict.fromkeys(pids))}
    pid_idx = np.fromiter((uniq_pids[p] for p in pids), dtype=np.int32,
                          count=len(pids))
    if vector_size:
        values = np.asarray(vals, dtype=np.float32).reshape(
            len(vals), vector_size)
    else:
        values = np.asarray(vals, dtype=np.float32)
    return EncodedData(pid=pid_idx, pk=pk_idx, values=values,
                       pk_vocab=pk_vocab, n_rows=len(pid_idx))


# ---------------------------------------------------------------------------
# The fused kernel
# ---------------------------------------------------------------------------


def _fused_kernel_body(config: FusedConfig, num_partitions: int, pid, pk,
                       values, valid, noise_scales, keep_table,
                       sel_threshold, sel_scale, sel_min_count,
                       sel_rows_per_uid, key, fx_bits: int,
                       kernel_backend: str):
    """The un-jitted aggregation body: shared verbatim by the solo
    kernel and the serve-fusion batched kernel (one vmapped request
    axis over this exact graph), so a fused request's arithmetic IS the
    solo request's arithmetic."""
    # Seeded entry seam: the ONE root split into the bounding /
    # selection / noise streams, pure in the caller's key.
    # lint: disable=rng-purity(root split seam, pure in caller's key)
    k_bound, k_sel, k_noise = jax.random.split(key, 3)
    part, part_nseg, qrows = _partials(config, num_partitions, pid, pk,
                                       values, valid, k_bound, fx_bits,
                                       kernel_backend=kernel_backend)
    return _selection_and_metrics(config, num_partitions, part, part_nseg,
                                  noise_scales, keep_table, sel_threshold,
                                  sel_scale, sel_min_count,
                                  sel_rows_per_uid, k_sel, k_noise,
                                  qrows=qrows)


@instrumented_jit(phase="engine", static_argnames=("config",
                                                   "num_partitions",
                                                   "fx_bits",
                                                   "kernel_backend"))
def fused_aggregate_kernel(config: FusedConfig, num_partitions: int, pid,
                           pk, values, valid, noise_scales, keep_table,
                           sel_threshold, sel_scale, sel_min_count,
                           sel_rows_per_uid, key, fx_bits: int = 7,
                           kernel_backend: str = "xla"):
    """One compiled program for the whole aggregation. See module docstring.

    Runtime inputs:
      pid, pk: int32[N] (padded); values: f32[N] or f32[N, D]; valid:
      bool[N] row mask; noise_scales: f32[0 or 1] — only the percentile
      tree's per-level scale (the scalar release runs on host, see
      _host_release); keep_table: f32[T] truncated-
      geometric keep probabilities (unused for thresholding strategies);
      sel_threshold/sel_scale: f32 scalars for thresholding strategies;
      key: PRNG key.
    """
    return _fused_kernel_body(config, num_partitions, pid, pk, values,
                              valid, noise_scales, keep_table,
                              sel_threshold, sel_scale, sel_min_count,
                              sel_rows_per_uid, key, fx_bits,
                              kernel_backend)


@instrumented_jit(phase="serve_fused", static_argnames=("config",
                                                        "num_partitions",
                                                        "fx_bits",
                                                        "kernel_backend"))
def fused_aggregate_batch_kernel(config: FusedConfig,
                                 num_partitions: int, pid, pk, values,
                                 valid, noise_scales, keep_table,
                                 sel_threshold, sel_scale, sel_min_count,
                                 sel_rows_per_uid, keys,
                                 fx_bits: int = 7,
                                 kernel_backend: str = "xla"):
    """One compiled program serving a whole BATCH of requests: every
    runtime input gains a leading request axis (``pid``: int32[B, N],
    ``keys``: [B] PRNG keys, scalar selection inputs become f32[B], ...)
    and the solo kernel body vmaps over it. Request b's slice computes
    bit-identically to a solo ``fused_aggregate_kernel`` call with the
    same inputs (PARITY row 35): the body is shared, per-request noise
    keys keep the streams pure (counter RNG is keyed by content), and
    the per-request ``valid`` row masks plus the padding-invariant
    tie-breaks (``counter_rng.row_bits``) guarantee bucket padding can
    never leak into released values. Dispatched ONLY from the blessed
    serve-fusion seam (``serve/fusion.py``; the ``fusion-masking``
    lint) — batch mode and the streaming planes never see it. The
    distinct program name keys the cost observatory's ``device_costs``
    signatures apart from solo programs, so roofline verdicts stay
    per-program."""
    def one(pid, pk, values, valid, scales, table, thr, s_scale,
            min_count, rows_per_uid, key):
        return _fused_kernel_body(config, num_partitions, pid, pk,
                                  values, valid, scales, table, thr,
                                  s_scale, min_count, rows_per_uid, key,
                                  fx_bits, kernel_backend)

    return jax.vmap(one)(pid, pk, values, valid, noise_scales,
                         keep_table, sel_threshold, sel_scale,
                         sel_min_count, sel_rows_per_uid, keys)


def _partials(config: FusedConfig, num_partitions: int, pid, pk, values,
              valid, key, fx_bits: int = 7,
              kernel_backend: str = "xla"):
    """Contribution bounding + per-pk accumulator partials. Shardable by
    privacy id: every pid's rows must live in one shard, pks may be
    spread — partials then combine across shards by plain addition
    (psum).

    Scatter-minimal design: on TPU a segment_sum/scatter over the row axis
    costs ~10x an elementwise op, so the kernel sorts ONCE by
    (pid, hash(pid, pk, salt), random) — pk itself is not a key: for a
    fixed pid the hash is injective in pk, so segments are contiguous
    already — and then derives every per-segment quantity in row space
    with cumsum/cummax (runs are contiguous after the sort). The hash key
    makes the within-pid segment
    order a fresh uniform permutation per run and per pid, so "ordinal
    within pid < l0" IS the L0 cross-partition sample — in (l0, linf)
    mode no second sort and no per-segment scatter are needed; the only
    scatters are the final per-pk reductions (and, for per-partition
    -bound sums, one per-segment total). Total-cap mode
    (``max_contributions``) pays one extra lexsort + row-space scatter
    for its uniform per-pid row sample — see the branch below."""
    n = pid.shape[0]
    P = num_partitions

    if config.bounds_already_enforced:
        # No privacy ids: every row is its own "segment"; no sampling.
        row_keep = valid
        pk_safe = jnp.where(valid, pk, 0)
        clipped = _clip_values(config, values)
        masked = jnp.where(_expand(row_keep, clipped), clipped, 0.0)
        if config.per_partition_bounds:
            # One row = one segment: the per-segment sum clip is a row clip.
            masked = jnp.where(
                row_keep,
                jnp.clip(masked, config.min_sum_per_partition,
                         config.max_sum_per_partition), 0.0)
        qrows = (_qrows(config, pk_safe, values, row_keep)
                 if config.percentiles else None)
        part, _ = _reduce_per_pk(config, pk_safe, masked, row_keep, masked,
                                 P, fx_bits=fx_bits,
                                 kernel_backend=kernel_backend)
        # Without pids every row counts as its own privacy unit
        # (reference dp_engine.py:341-348 works off row counts).
        part_nseg = part["count"]
        return part, part_nseg, qrows

    from pipelinedp_tpu.ops import counter_rng

    # Blessed seam: tie-break/salt/sample bits for contribution
    # bounding, all derived from the bounding stream's key. Row-space
    # tie-breaks come from the counter generator keyed by ROW POSITION
    # (``counter_rng.row_bits``), not ``jax.random.bits`` — the
    # latter's counter pairing depends on the padded length, which
    # would couple the sampled contribution subsets to how far the row
    # axis is padded. Content-keyed bits make every released value a
    # pure function of (key, real rows): padding the same request to a
    # larger pow2 fusion bucket is bit-identical to its solo padding
    # (PARITY row 35, asserted in tests/test_fusion.py).
    # lint: disable=rng-purity(bounding tie-break bits, keyed by k_bound)
    k_tie, k_salt, k_m = jax.random.split(key, 3)
    # lint: disable=rng-purity(per-run salt from the bounding stream)
    salt = jax.random.bits(k_salt, (), dtype=jnp.uint32)
    tiebreak = counter_rng.row_bits(k_tie, n)
    big_pid = jnp.where(valid, pid, seg_ops.PAD_ID)
    big_pk = jnp.where(valid, pk, seg_ops.PAD_ID)
    # Sampling priority of segment (pid, pk): an independent uniform
    # permutation of each pid's partitions (salted per run).
    hpk = seg_ops.fmix32(
        seg_ops.fmix32(big_pid.astype(jnp.uint32) ^ salt) ^
        big_pk.astype(jnp.uint32))
    # For fixed (pid, salt), pk -> hpk is injective (fmix32 is a bijection
    # on uint32 composed with an xor by a per-pid constant), so (pid, hpk)
    # already identifies the (pid, pk) segment — pk itself is redundant as
    # a sort key, cutting one operand from the sort network.
    sort_idx = jnp.lexsort((tiebreak, hpk, big_pid))
    spid = big_pid[sort_idx]
    spk = big_pk[sort_idx]
    # COUNT-style metrics never read the value column: skip the gather of
    # the (all-zero) values array entirely.
    svalues = values[sort_idx] if config.needs_values else values
    idx = jnp.arange(n)
    # Valid rows sort before padding (PAD_ID keys): no gather needed.
    svalid = idx < jnp.sum(valid.astype(jnp.int32))

    new_pid = (idx == 0) | (spid != jnp.roll(spid, 1))
    new_seg = new_pid | (spk != jnp.roll(spk, 1))
    if config.max_contributions is not None:
        # Total-cap mode: a uniform without-replacement sample of M rows
        # per privacy unit, across all its partitions (the fused twin of
        # SamplingPerPrivacyIdContributionBounder). The sample must be
        # uniform over the unit's ROWS, not follow the hpk segment order,
        # so rank rows by an independent random key in a second sort and
        # carry the keep bits back through the permutations.
        tie_m = counter_rng.row_bits(k_m, n)
        order_m = jnp.lexsort((tie_m, big_pid))
        mpid = big_pid[order_m]
        new_pid_m = (idx == 0) | (mpid != jnp.roll(mpid, 1))
        keep_sorted = seg_ops.rank_in_run(new_pid_m) < config.max_contributions
        keep_m = jnp.zeros(n, bool).at[order_m].set(keep_sorted)
        keep_row = svalid & keep_m[sort_idx]
        # First KEPT row of each segment marks the (pid, pk) pair as
        # contributing; fully-sampled-away segments must not count
        # toward the privacy-id count or selection.
        wk = jnp.cumsum(keep_row.astype(jnp.int32))
        seg_start = seg_ops.run_starts(new_seg)
        kept_before_seg = wk[seg_start] - keep_row[seg_start]
        seg_marker = keep_row & (wk == kept_before_seg + 1)
    else:
        # Linf bound: keep the first linf (randomly ordered) rows per
        # segment.
        linf_cap = config.linf if config.linf is not None else n
        row_keep = svalid & (seg_ops.rank_in_run(new_seg) < linf_cap)
        # L0 bound: the segment's ordinal within its pid — uniform by the
        # hpk sort key — must be < l0.
        keep_l0 = seg_ops.run_ordinal_in_group(new_seg,
                                               new_pid) < config.l0
        keep_row = row_keep & keep_l0
        # Kept-segment indicator on the segment's first row: the per-pk
        # sum of these is the privacy-id count (row_count in the
        # reference's compound accumulator, dp_engine.py:339).
        seg_marker = new_seg & svalid & keep_l0

    clipped = _clip_values(config, svalues)
    masked = jnp.where(_expand(keep_row, clipped), clipped, 0.0)
    pk_safe = jnp.where(svalid, spk, 0)

    if config.per_partition_bounds:
        # Clip each (pid, pk) segment's SUM, contributed once per segment.
        # seg_ord is monotone, so this segment_sum is the one per-segment
        # scatter this mode still needs; precision-safe (no cumsum diff).
        seg_ord = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
        seg_total = jax.ops.segment_sum(masked, seg_ord, num_segments=n)
        tot_row = seg_total[seg_ord]
        contrib = jnp.where(
            seg_marker,
            jnp.clip(tot_row, config.min_sum_per_partition,
                     config.max_sum_per_partition), 0.0)
        part, part_nseg = _reduce_per_pk(config, pk_safe, masked, keep_row,
                                         contrib, P, seg_marker=seg_marker,
                                         fx_bits=fx_bits,
                                         kernel_backend=kernel_backend)
    else:
        part, part_nseg = _reduce_per_pk(config, pk_safe, masked, keep_row,
                                         None, P, seg_marker=seg_marker,
                                         fx_bits=fx_bits,
                                         kernel_backend=kernel_backend)

    qrows = (_qrows(config, spk, svalues, keep_row)
             if config.percentiles else None)
    return part, part_nseg, qrows


# Fixed-point value accumulation: quantization grid (2^23 steps over the
# clip bound) split into integer lanes whose int32 segment sums stay
# EXACT. The lane width adapts to the (global) row count: a lane of
# ``bits`` bits accumulates up to 2^31/(2^bits - 1) rows exactly, so
# small datasets ride two wide 12-bit lanes (narrower scatter payload)
# and huge ones six 4-bit lanes (capacity 2^27 rows across the mesh).
_FX_STEPS = 1 << 23
_FX_OFFSET = 1 << 23
_FX_PAYLOAD_BITS = 24  # offset-shifted u fits 24 bits (u <= 2^24 - 1)

# int32 lane-sum capacity. Module-level seam so boundary tests can
# inject a small cap and pin the exact guard cliff (the way the lane
# plan's 524,417-row boundary is pinned) without 2^27-row datasets.
_LANE_SUM_CAP = 1 << 31


def _fx_max_rows() -> int:
    """Largest per-batch GLOBAL row count the narrowest (4-bit) lane
    plan accumulates exactly — the streaming chunk sizer caps per-batch
    targets here so value pipelines never plan an impossible batch."""
    return (_LANE_SUM_CAP - 1) // 15


def _fx_plan(n_rows_total: int) -> Tuple[int, int]:
    """(lane_bits, n_lanes) for a pipeline with ``n_rows_total`` rows
    across all devices — the cross-device psum adds per-shard lane sums,
    so capacity is a GLOBAL row bound."""
    bits = 12
    while bits > 4 and n_rows_total * ((1 << bits) - 1) >= _LANE_SUM_CAP:
        bits -= 1
    if n_rows_total * ((1 << bits) - 1) >= _LANE_SUM_CAP:
        raise NotImplementedError(
            f"fixed-point value lanes support up to 2^27 rows per "
            f"BATCH (got {n_rows_total}). The engine streams larger "
            "pipelines automatically (pipelinedp_tpu.streaming, "
            "including percentiles, with or without a mesh); reaching "
            "this from the streaming path means one privacy unit owns "
            "that many rows (its rows cannot split across batches)")
    return bits, -(-_FX_PAYLOAD_BITS // bits)


@dataclasses.dataclass(frozen=True)
class _FxSpec:
    """One fixed-point accumulated value column."""
    name: str
    bound: float  # |y| <= bound
    signed: bool  # signed columns ship offset by _FX_OFFSET
    count_col: str  # column holding the number of contributing entries

    @property
    def scale(self) -> float:
        return (_FX_STEPS - 1) / self.bound if self.bound > 0 else 1.0


def _fixedpoint_layout(config: FusedConfig) -> List[_FxSpec]:
    """The value columns the kernel accumulates in fixed point. Static in
    the config, so kernel and host release agree on the encoding."""
    names = set(config.metrics)
    if "VECTOR_SUM" in names or not (names & {"SUM", "MEAN", "VARIANCE"}):
        return []
    if config.per_partition_bounds:
        bound = max(abs(config.min_sum_per_partition),
                    abs(config.max_sum_per_partition))
        # One contribution per kept (pid, pk) segment.
        return [_FxSpec("sum", bound, True, "privacy_id_count_raw")]
    r = (config.max_value - config.min_value) / 2.0
    specs = [_FxSpec("nsum", r, True, "count")]
    if "VARIANCE" in names:
        specs.append(_FxSpec("nsumsq", r * r, False, "count"))
    return specs


def _vector_fx(config: FusedConfig) -> bool:
    """Whether VECTOR_SUM accumulates in fixed-point coordinate lanes
    (the ``vector_accumulator`` knob resolved onto the config). Static
    in the config, so kernel, host fold and streaming sizer agree."""
    return ("VECTOR_SUM" in config.metrics
            and config.vector_accumulator == "fx")


def _vector_fx_scale(config: FusedConfig) -> float:
    """Quantization scale of the vector coordinate grid: 2^23 - 1 steps
    over the static norm clip bound. The quantizer's clamp doubles as a
    per-row coordinate clamp at ±vector_max_norm — a contraction applied
    BEFORE aggregation (never increases sensitivity; the release still
    norm-clips the per-partition sum at the same bound), and one of the
    two documented ways 'fx' and 'f32' releases may differ (README
    "Vector aggregation")."""
    bound = float(config.vector_max_norm or 0.0)
    return (_FX_STEPS - 1) / bound if bound > 0 else 1.0


def _reduce_per_pk(config: FusedConfig, pk_safe, masked, keep_row,
                   per_partition_sum_contrib, P, seg_marker=None,
                   fx_bits: int = 7, kernel_backend: str = "xla"):
    """The fused shuffle 3: per-pk accumulator columns straight from row
    space, returned as (columns dict, privacy-id-count column).

    Every scalar column accumulates in int32 (VECTOR_SUM is the one
    exception — see below) — in ONE multi-feature segment_sum
    up to 2^24 rows (the scatter's addressing pass is shared; only the
    payload widens), and in independent per-column scatters beyond that
    (XLA tile-pads a [N, C] operand's C dim to 128 lanes and materializes
    a 21x remat copy at 2^25 rows):

    * counts + kept-segment markers directly — float32 addition saturates
      at 2^24 (1.0 + 16777216.0 == 16777216.0), silently under-counting
      huge partitions; int32 is exact to 2^31;
    * value columns in FIXED POINT: the normalized value
      (x - midpoint, and its square for variance — normalizing on device
      also kills the f32 cancellation of the sumsq recombination) is
      quantized to a 2^23-step grid over its static clip bound and split
      into four 7-bit lanes, each an exact int32 segment sum; the host
      release reassembles lanes in float64 (``_fold_fixedpoint``). Unlike
      a monolithic f32 segment_sum — whose sequential rounding drifts
      unboundedly with partition size (saturating outright at 2^24 equal
      values) — the only error is the per-row quantization, bounded by
      bound/2^23 per row independent of partition size, far below the
      f32 representation error of the inputs themselves.

    TPU-first rationale: the chip has no fast f64; exact integer lanes +
    one wide scatter beat both emulated f64 (x64 flag, 2x sort payload)
    and compensated-float scans (sequential chunk loop, still drifts on
    adversarial equal-value streams).
    """
    names = set(config.metrics)
    int_cols = [keep_row.astype(jnp.int32)]
    lane_names: List[str] = []
    if seg_marker is not None:
        int_cols.append(seg_marker.astype(jnp.int32))

    layout = _fixedpoint_layout(config)
    n_lanes = -(-_FX_PAYLOAD_BITS // fx_bits)
    if (layout or _vector_fx(config)) and max(
            pk_safe.shape[0] - 8191, 1) * (
            (1 << fx_bits) - 1) >= (1 << 31):
        # Loud trace-time guard for direct kernel callers: lane sums past
        # int32 capacity would wrap silently. The kernel only sees the
        # PADDED shape (real rows + at most 8191 padding rows, which are
        # masked to zero and consume no capacity), hence the 8191-row
        # allowance; _run_fused_kernel sizes fx_bits from the real global
        # row count, so the engine path never trips this.
        raise NotImplementedError(
            f"{pk_safe.shape[0]} (padded) rows overflow {fx_bits}-bit "
            "fixed-point lanes; pass a smaller fx_bits (see _fx_plan)")
    for spec in layout:
        if spec.name == "sum":  # per-partition-bound mode
            y = per_partition_sum_contrib
            mask = seg_marker if seg_marker is not None else keep_row
        elif spec.name == "nsum":
            middle = dp_computations.compute_middle(config.min_value,
                                                    config.max_value)
            y = masked - middle
            mask = keep_row
        else:  # nsumsq
            middle = dp_computations.compute_middle(config.min_value,
                                                    config.max_value)
            y = (masked - middle) * (masked - middle)
            mask = keep_row
        # Clamp after rounding: f32 rounding of y*scale at the clip
        # boundary can land one step past ±(2^23 - 1), which would need a
        # 25th payload bit; the clamp costs one grid step of accuracy at
        # the exact boundary and keeps u <= 2^24 - 1 in 24 bits.
        q = jnp.clip(jnp.round(y * spec.scale), -(_FX_STEPS - 1),
                     _FX_STEPS - 1).astype(jnp.int32)
        u = jnp.where(mask, q + (_FX_OFFSET if spec.signed else 0), 0)
        for k in range(n_lanes):
            int_cols.append((u >> (k * fx_bits)) & ((1 << fx_bits) - 1))
            lane_names.append(f"{spec.name}_fx{k}")

    if len(int_cols) == 1:
        ints = [jax.ops.segment_sum(int_cols[0], pk_safe, num_segments=P)]
    elif pk_safe.shape[0] >= (1 << 25):
        # Past 2^24 rows XLA materializes a tile-padded remat copy of the
        # [N, C] stack (the C-sized dim pads to 128 lanes — a 21x, 16GB
        # blowup at 2^25); independent per-column scatters keep every
        # operand rank-1 and densely tiled.
        ints = [jax.ops.segment_sum(c, pk_safe, num_segments=P)
                for c in int_cols]
    else:
        # One multi-feature scatter: the addressing pass is shared.
        # The ``kernel_backend`` knob swaps in the Pallas lane-packed
        # segment sum here (bit-identical int32 totals — PARITY row
        # 33); off-envelope shapes or a Pallas-less host fall back to
        # the XLA scatter with a ``kernel.fallback`` event.
        from pipelinedp_tpu.ops import kernels as hot_kernels
        stack = jnp.stack(int_cols, axis=1)
        stacked = hot_kernels.try_segment_sum_lanes(
            stack, pk_safe, P, kernel_backend)
        if stacked is None:
            stacked = jax.ops.segment_sum(stack, pk_safe,
                                          num_segments=P)
        ints = [stacked[:, i] for i in range(len(int_cols))]
    part = {"count": ints[0]}
    col = 1
    if seg_marker is not None:
        nseg = ints[col]
        col += 1
    else:
        nseg = None
    for i, name in enumerate(lane_names):
        part[name] = ints[col + i]

    if "VECTOR_SUM" in names:
        if _vector_fx(config):
            # Fixed-point coordinate lanes — the scalar columns'
            # discipline at [N, D] width: each coordinate quantizes to
            # the 2^23-step grid over the norm clip bound, the
            # offset-shifted payload splits into n_lanes int32 lane
            # planes concatenated lane-major ([N, n_lanes*D]), and ONE
            # wide segment sum reduces them per partition — exact
            # int32 totals, backend- and mesh-bit-identical (PARITY
            # row 39). The host fold (_fold_vector_fx_steps)
            # reassembles float64 coordinates.
            scale = _vector_fx_scale(config)
            q = jnp.clip(jnp.round(masked * scale), -(_FX_STEPS - 1),
                         _FX_STEPS - 1).astype(jnp.int32)
            u = jnp.where(keep_row[:, None], q + _FX_OFFSET, 0)
            lanes = jnp.concatenate(
                [(u >> (k * fx_bits)) & ((1 << fx_bits) - 1)
                 for k in range(n_lanes)], axis=1)
            from pipelinedp_tpu.ops import kernels as hot_kernels
            vec = hot_kernels.try_segment_sum_wide(
                lanes, pk_safe, P, kernel_backend,
                d_block=config.wide_d_block)
            if vec is None:
                vec = jax.ops.segment_sum(lanes, pk_safe,
                                          num_segments=P)
            part["vector_sum"] = vec
        else:
            # Vector coordinates accumulate in float32 (the historical
            # default; the 'fx' accumulator above retires the hazard).
            # The f32 drift/saturation hazard the lanes eliminate for
            # scalars still applies per coordinate past ~2^24 equal
            # contributions in one partition (README "Scaling
            # limits"). The Pallas wide-D kernel never dispatches here
            # — an f32 matmul's partial-sum order differs from the XLA
            # scatter's, so bit-identity would not hold; a pallas
            # request degrades visibly instead.
            if kernel_backend == "pallas":
                from pipelinedp_tpu import obs
                obs.inc("kernel.fallbacks")
                obs.event("kernel.fallback", site="segment_sum_wide",
                          reason="vector_f32_accumulator",
                          P=int(P), D=int(masked.shape[1]),
                          rows=int(pk_safe.shape[0]))
            part["vector_sum"] = jax.ops.segment_sum(masked, pk_safe,
                                                     num_segments=P)
    return part, nseg


def _fold_fx_steps(config: FusedConfig, part64, fx_bits: int) -> None:
    """Reassembles the fixed-point lane columns into EXACT step totals
    (mutates ``part64``): steps = sum of lanes * 2^(bits*k) - entries *
    offset. Every term is an integer below 2^53, so the float64 result
    is exact — which is what lets the streaming fold accumulate these
    across chunks and divide by the (non-power-of-two) scale ONCE at
    release: a per-chunk division would round per chunk, making the
    released low bits a function of the batch boundaries (and therefore
    of the mesh size — the elastic reshard-resume parity would only
    hold by luck)."""
    n_lanes = -(-_FX_PAYLOAD_BITS // fx_bits)
    for spec in _fixedpoint_layout(config):
        total = np.zeros_like(part64[spec.count_col], dtype=np.float64)
        for k in range(n_lanes):
            total += part64.pop(f"{spec.name}_fx{k}").astype(
                np.float64) * float(1 << (k * fx_bits))
        if spec.signed:
            total -= part64[spec.count_col].astype(np.float64) * _FX_OFFSET
        part64[spec.name] = total


def _fold_vector_fx_steps(config: FusedConfig, lanes, count,
                          fx_bits: int):
    """Reassembles the [n, n_lanes*D] vector lane sums into EXACT
    float64 step totals [n, D]: steps = sum of lane planes * 2^(bits*k)
    - count * offset. Same exactness contract as
    :func:`_fold_fx_steps` — every term is an integer below 2^53, so
    the streaming fold may accumulate step totals across chunks and
    divide by the scale ONCE at release (batch-boundary invariant; the
    elastic reshard-resume parity depends on it)."""
    n_lanes = -(-_FX_PAYLOAD_BITS // fx_bits)
    D = int(config.vector_size)
    lanes = np.asarray(lanes)
    total = np.zeros((lanes.shape[0], D), dtype=np.float64)
    for k in range(n_lanes):
        total += lanes[:, k * D:(k + 1) * D].astype(
            np.float64) * float(1 << (k * fx_bits))
    total -= np.asarray(count).astype(np.float64)[:, None] * _FX_OFFSET
    return total


def _fold_fixedpoint(config: FusedConfig, part64, fx_bits: int) -> None:
    """Reassembles the fixed-point lane columns into float64 values
    (mutates ``part64``): value = (sum of lanes * 2^(bits*k) - entries *
    offset) / scale. ``entries`` (the per-partition count of contributing
    rows/segments) is exact int, so the offset removal is exact. The
    vector lanes fold the same way ([n, D] coordinates from the
    lane-major [n, n_lanes*D] sums, offsets removed via the count
    column)."""
    _fold_fx_steps(config, part64, fx_bits)
    for spec in _fixedpoint_layout(config):
        part64[spec.name] = part64[spec.name] / spec.scale
    if _vector_fx(config) and "vector_sum" in part64:
        part64["vector_sum"] = _fold_vector_fx_steps(
            config, part64["vector_sum"], part64["count"],
            fx_bits) / _vector_fx_scale(config)


def _qrows(config: FusedConfig, pk, values, kept):
    """Percentile row view: (pk, leaf index, kept mask) per row, in
    whatever row order the caller is in. The leaf mapping mirrors the host
    tree (``ops/quantile_tree.py:_leaf_index``).

    The leaf arithmetic is one f32 subtract and one f32 multiply by a
    host-folded constant — deliberately: the streamed pass-A and pass-B
    kernels are SEPARATE XLA programs that re-derive each row's leaf, and
    a division (whose lowering can vary with fusion context) or a
    fusible mul+add pair (FMA) could round differently across programs,
    silently mis-bucketing boundary values between the passes. Neither
    op here is re-fusible (sub->mul is not an FMA pattern), so every
    program computes the identical IEEE sequence."""
    b = quantile_tree_ops.DEFAULT_BRANCHING_FACTOR
    height = quantile_tree_ops.DEFAULT_TREE_HEIGHT
    n_leaves = b**height
    lower, upper = config.min_value, config.max_value
    v = jnp.clip(values, lower, upper)
    rng = float(upper) - float(lower)
    inv_range = np.float32(float(n_leaves) / rng) if rng > 0 else None
    # ``params_are_fusable`` routes degenerate (lower >= upper) and
    # pathologically tiny ranges (f32-overflowing constant) to the host
    # path, which computes in f64; a non-finite constant here means a
    # FusedConfig was constructed around that guard.
    assert inv_range is not None and np.isfinite(inv_range), (
        f"fused percentile range [{lower}, {upper}] has no finite f32 "
        "leaf constant — params_are_fusable should have rejected it")
    leaf = jnp.minimum(((v - lower) * inv_range).astype(jnp.int32),
                       n_leaves - 1)
    return (jnp.where(kept, pk, 0), leaf, kept)


def _selection_and_metrics(config: FusedConfig, num_partitions: int, part,
                           part_nseg, noise_scales, keep_table,
                           sel_threshold, sel_scale, sel_min_count,
                           sel_rows_per_uid, k_sel, k_noise, qrows=None,
                           pk_axis=None, pk_axis_size=1, pk_topo=None):
    """Batched partition selection + metric noising.

    Single-chip: ``num_partitions`` is the full pk axis. Multi-chip
    (``pk_axis`` set): the partition axis is SHARDED — ``part``/
    ``part_nseg`` are this device's owned block of ``num_partitions``
    partitions (out of ``num_partitions * pk_axis_size`` global), after
    the ``psum_scatter`` exchange in ``parallel.sharded``. Selection
    randomness is drawn over the GLOBAL axis and sliced, so the mesh
    computes bit-identical keep decisions to a single device with the
    same key whenever the global axis equals the single-chip padded axis
    (any power-of-two mesh; see ``sharded_fused_aggregate``'s rounding
    note)."""
    from pipelinedp_tpu.ops import noise as noise_ops

    P = num_partitions
    if pk_axis is None:
        offset = None
        P_total = P
    else:
        offset = jax.lax.axis_index(pk_axis) * P
        P_total = P * pk_axis_size

    def owned(draw_fn):
        """Draws a [P_total] random vector, returns this device's block."""
        full = draw_fn((P_total,))
        if offset is None:
            return full
        return jax.lax.dynamic_slice(full, (offset,), (P,))

    # --- partition selection (batched over all partitions) ---
    if config.selection is None:
        keep_pk = jnp.ones(P, dtype=bool)
        # (The public-partition empty-accumulator sum adjustment happens
        # in the float64 host release, _host_release.)
    else:
        # Without privacy ids one row is not one user; the conservative
        # user-count estimate is ceil(rows / max_rows_per_privacy_id)
        # (reference dp_engine.py:341-348).
        est_users = jnp.ceil(part_nseg.astype(jnp.float32) /
                             sel_rows_per_uid)
        counts = est_users.astype(jnp.int32)
        if config.selection == (
                PartitionSelectionStrategy.TRUNCATED_GEOMETRIC):
            idx = jnp.clip(counts, 0, keep_table.shape[0] - 1)
            p_keep = keep_table[idx]
            # Selection draws route through the blessed noise module
            # (unit scale here; sel_scale applies outside the draw).
            keep_pk = owned(
                lambda s: noise_ops.jax_uniform(k_sel, s)) < p_keep
        else:
            if config.selection == (
                    PartitionSelectionStrategy.LAPLACE_THRESHOLDING):
                noise_sel = owned(
                    lambda s: noise_ops.jax_laplace(k_sel, s, 1.0)
                ) * sel_scale
            else:
                noise_sel = owned(
                    lambda s: noise_ops.jax_gaussian(k_sel, s, 1.0)
                ) * sel_scale
            keep_pk = ((est_users + noise_sel) >= sel_threshold) & (
                est_users >= sel_min_count)  # pre-threshold hard floor
        keep_pk = keep_pk & (part_nseg > 0)

    # --- accumulator partials out; the scalar release happens on HOST in
    # float64 (see LazyFusedResult._host_release): float32 noise on a
    # large aggregate quantizes to the value's ULP grid, which both
    # distorts the calibrated distribution and leaks through rounding
    # (the reference's release path is float64 end-to-end). Percentiles
    # stay on device: their noisy node counts are small integers where
    # float32 granularity is irrelevant, and the walk needs the rows.
    out = dict(part)
    out["privacy_id_count_raw"] = part_nseg
    if config.percentiles:
        # Percentile noise scale is the last _noise_scales entry; the tree
        # key is independent of the selection key stream.
        # lint: disable=rng-purity(tree key: constant fold of the noise stream)
        k_tree = jax.random.fold_in(k_noise, 0x7ee)
        if pk_axis is None:
            vals = _percentile_values(config, P, qrows, noise_scales[-1],
                                      k_tree)
        else:
            vals = _percentile_values_owned(config, P, qrows,
                                            noise_scales[-1], k_tree,
                                            pk_axis, pk_axis_size,
                                            topo=pk_topo)
        for qi, name in enumerate(_percentile_field_names(
                config.percentiles)):
            out[name] = vals[:, qi]
    return keep_pk, out


def _percentile_field_names(percentiles) -> List[str]:
    """Same formatting as ``QuantileCombiner.metrics_names`` (reference
    ``combiners.py:434-445``)."""
    names = []
    for p in percentiles:
        int_p = int(round(p))
        text = str(int_p) if int_p == p else str(p).replace(".", "_")
        names.append(f"percentile_{text}")
    return names


def _node_noise(noise_kind: NoiseKind, key, node_ids, pk_index=None):
    """One noise draw per (partition, tree node), as a pure function of
    the indices: every quantile walk that visits a node sees the same
    noisy count — the stateless form of the host tree's memoization
    (``ops/quantile_tree.py::compute_quantiles``). Realized as ONE
    batched counter-based threefry pass per call
    (``ops/counter_rng.py``): the (partition, node) pair IS the
    counter, so the draw is identical wherever and however often the
    pair appears — visited-node-only draws, the root-level broadcast
    and partition-block-chunked walks are all bit-exact restructurings
    by construction. ``node_ids`` is int32 [P, Q, b]; ``pk_index``
    overrides the per-partition counter lane (the GLOBAL partition ids
    when the pk axis is sharded or block-chunked, so mesh, streamed and
    chunked noise all match the single-chip draw bit-for-bit)."""
    from pipelinedp_tpu.ops import counter_rng

    P = node_ids.shape[0]
    if pk_index is None:
        pk_index = jnp.arange(P, dtype=jnp.uint32)
    x0 = jnp.broadcast_to(
        pk_index.astype(jnp.uint32).reshape(
            (P,) + (1,) * (node_ids.ndim - 1)), node_ids.shape)
    x1 = node_ids.astype(jnp.uint32)
    if noise_kind == NoiseKind.LAPLACE:
        return counter_rng.laplace(key, x0, x1)
    return counter_rng.normal(key, x0, x1)


# HBM cap for the per-quantile subtree histogram (int32 [P, Q, span]);
# above it the walk chunks the partition axis into blocks and walks
# block-by-block (bit-identical to the unchunked walk — node noise is a
# pure function of (partition, node id)). Registered as the
# ``subhist_byte_cap`` knob; reads flow through ``plan.knobs`` (env >
# this seam when test-mutated > plan file > this default) and the
# module name survives as the test seam (``make noknobs``).
_SUBHIST_BYTE_CAP = 600 << 20

#: The ``vector_accumulator`` knob's module seam (plan/knobs.py
#: registers it, dp-UNSAFE — never planned): VECTOR_SUM's 'f32' vs
#: 'fx' accumulator discipline, resolved onto FusedConfig at
#: from_params time.
_VECTOR_ACCUMULATOR = "f32"

# The single-batch walk unrolls its partition blocks INSIDE one XLA
# program, so the block count is bounded: each block costs ~3 O(n)
# elementwise passes + Q compacted scatters, so 16 blocks stay well
# under the per-level row-scatter fallback's cost envelope while
# covering subtree blocks to 16x the byte cap (~10 GB at the default
# cap — past any single chip's HBM); beyond that the per-level
# fallback both bounds the program size and does fewer row passes.
# (The streamed walk needs no such bound: its blocks are separate
# kernel launches, and re-streaming is its only completion path.)
_MAX_WALK_BLOCKS = 16


def _percentile_values(config: FusedConfig, P, qrows, scale, key):
    """Batched DP quantile-tree descent over every partition at once
    (single-chip; the sharded twin is ``_percentile_values_owned``).

    Level l needs, per (partition, quantile), the noisy counts of the
    ``b`` children of the walk's current node. Rather than materializing
    per-partition trees, each level counts its children with one
    segment_sum over the rows (a row lands in child ``leaf//w - base``
    of its partition's walk, or nowhere). The arithmetic (rank targeting,
    child pick, interpolation, early stop when no noisy signal remains,
    monotone post-processing) mirrors ``QuantileTree.compute_quantiles``.
    """
    qpk, leaf, kept = qrows
    b, height, n_mid, bucket_w = quantile_tree_ops.tree_constants()
    quantiles = np.asarray([p / 100.0 for p in config.percentiles],
                           np.float32)
    Q = quantiles.shape[0]
    lower = float(config.min_value)
    upper = float(config.max_value)

    # Fast path: one [P, b^2] histogram (bucket width b^(height-2)),
    # built with ONE row scatter, serves the top two levels via P-space
    # sums/gathers — full-row scatters are the walk's dominant cost, so
    # this trades 2 of the 4 away. Wider histograms don't pay: [P, b^3]
    # is a 536M-segment scatter plus 2GB temps.
    hist = None
    if height >= 2:
        hist = jax.ops.segment_sum(
            kept.astype(jnp.int32),
            qpk * n_mid + jnp.minimum(leaf // bucket_w, n_mid - 1),
            num_segments=P * n_mid).reshape(P, n_mid)

    def counts_at(w, base):
        """Noiseless child counts [P, Q, b] of the walk nodes whose
        children have width ``w``."""
        if hist is not None and w >= bucket_w:
            return _mid_level_counts(hist, base, w, bucket_w, b)
        # Fallback for the lower levels: per-quantile row passes (an
        # interleaved [n*Q] scatter benches slower than Q separate [n]
        # scatters on TPU).
        counts = []
        for q in range(Q):
            slot = leaf // w - base[:, q][qpk]
            ok = kept & (slot >= 0) & (slot < b)
            seg = qpk * b + jnp.clip(slot, 0, b - 1)
            counts.append(
                jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                    num_segments=P * b).reshape(P, b))
        return jnp.stack(counts, axis=1).astype(jnp.float32)

    lo = jnp.full((P, Q), lower, jnp.float32)
    hi = jnp.full((P, Q), upper, jnp.float32)
    target = jnp.broadcast_to(quantiles[None, :], (P, Q))
    leaf_lo = jnp.zeros((P, Q), jnp.int32)
    done = jnp.zeros((P, Q), bool)
    level_offset = 0
    # Top levels: served by the mid histogram (node width >= bucket_w —
    # levels 0 and 1 for any height >= 2).
    n_top = min(2, height) if hist is not None else 0
    for level in range(n_top):
        w = b**(height - 1 - level)
        base = leaf_lo // w  # [P, Q] first-child index at this level
        lo, hi, target, leaf_lo, done = _walk_level(
            config.noise_kind, key, scale, counts_at(w, base), base,
            level_offset, lo, hi, target, leaf_lo, done, b, w)
        level_offset += b**(level + 1)

    if n_top < height:
        # Bottom levels: ONE leaf-granularity scatter per quantile over
        # the chosen subtree (span = w*b leaves at the first bottom
        # level) serves ALL remaining levels via in-register group sums
        # — halving the walk's dominant cost, the full-row scatters
        # (VERDICT r2 #9).
        w1 = b**(height - 1 - n_top)
        span = w1 * b
        # Which bottom-walk path fires is a STATIC (shape-driven)
        # decision made here, at jit-trace time — so the ledger records
        # it once per compiled shape (scope="compile"), exactly when
        # the choice happens; cached executions re-use the program and
        # the recorded choice with it.
        from pipelinedp_tpu import obs
        from pipelinedp_tpu import plan as plan_mod
        # The execution planner's resolution of the subhist byte cap
        # (env > test seam > plan file > default; the module constant
        # survives as the seam). Host code at jit-trace time, so the
        # choice is recorded once per compiled shape like the walk
        # path itself.
        subhist_cap = int(plan_mod.knob_value("subhist_byte_cap"))
        if P * Q * span * 4 <= subhist_cap:
            obs.inc("walk.path_subhist")
            obs.event("walk.path", path="subhist", scope="compile",
                      P=int(P), Q=int(Q), span=int(span))
            sub_start = leaf_lo  # [P, Q] first leaf of each subtree
            sub_hist = _build_sub_hist(qpk, leaf, kept, sub_start, P, Q,
                                       span, b, height)
            for level in range(n_top, height):
                w = b**(height - 1 - level)
                raw = _sub_level_counts(sub_hist, sub_start, leaf_lo, w, b)
                lo, hi, target, leaf_lo, done = _walk_level(
                    config.noise_kind, key, scale, raw, leaf_lo // w,
                    level_offset, lo, hi, target, leaf_lo, done, b, w)
                level_offset += b**(level + 1)
        else:
            blk = 0
            if Q * span * 4 <= subhist_cap:
                blk = min(P, 1 << ((subhist_cap //
                                    (Q * span * 4)).bit_length() - 1))
            if blk and -(-P // blk) <= _MAX_WALK_BLOCKS:
                obs.inc("walk.path_partition_block_chunked")
                obs.event("walk.path", path="partition_block_chunked",
                          scope="compile", blk=int(blk), P=int(P))
                # The full [P, Q, span] block would blow the HBM cap:
                # chunk the partition axis into blocks and walk
                # block-by-block (the streamed pass B's q-chunk loop
                # shape, turned along the partition axis), each block's
                # histogram built with the SAME compacted machinery as
                # the one-block walk (rows outside the block are simply
                # masked out of the relevance flags). Node noise is a
                # pure function of the GLOBAL (partition, node id) —
                # passed via ``pk_index`` — and the per-partition
                # histogram content is unchanged, so the chunked walk
                # is bit-identical to the unchunked one.
                outs = []
                for p0 in range(0, P, blk):
                    Pb = min(blk, P - p0)
                    psl = slice(p0, p0 + Pb)
                    ss = leaf_lo[psl]
                    rel_pk = qpk - p0
                    kept_b = kept & (rel_pk >= 0) & (rel_pk < Pb)
                    pk_b = jnp.clip(rel_pk, 0, Pb - 1)
                    sub = _build_sub_hist(pk_b, leaf, kept_b, ss, Pb,
                                          Q, span, b, height)
                    lo_b, hi_b, tg_b = lo[psl], hi[psl], target[psl]
                    ll_b, dn_b = leaf_lo[psl], done[psl]
                    pk_idx = (p0 + jnp.arange(Pb)).astype(jnp.uint32)
                    lvo = level_offset
                    for level in range(n_top, height):
                        w = b**(height - 1 - level)
                        raw = _sub_level_counts(sub, ss, ll_b, w, b)
                        lo_b, hi_b, tg_b, ll_b, dn_b = _walk_level(
                            config.noise_kind, key, scale, raw,
                            ll_b // w, lvo, lo_b, hi_b, tg_b, ll_b,
                            dn_b, b, w, pk_index=pk_idx)
                        lvo += b**(level + 1)
                    outs.append(lo_b + (hi_b - lo_b) * tg_b)
                return _monotone_in_q(jnp.concatenate(outs, axis=0),
                                      quantiles)
            # Past _MAX_WALK_BLOCKS (or a cap below one partition's
            # [1, Q, span] block — necessarily test-shrunken):
            # per-level per-quantile row scatters, the rows being
            # device-resident here.
            obs.inc("walk.path_per_level_scatter")
            obs.event("walk.path", path="per_level_scatter",
                      scope="compile", P=int(P), Q=int(Q))
            for level in range(n_top, height):
                w = b**(height - 1 - level)
                base = leaf_lo // w
                lo, hi, target, leaf_lo, done = _walk_level(
                    config.noise_kind, key, scale, counts_at(w, base),
                    base, level_offset, lo, hi, target, leaf_lo, done,
                    b, w)
                level_offset += b**(level + 1)
    vals = lo + (hi - lo) * target  # [P, Q]
    return _monotone_in_q(vals, quantiles)


def _build_sub_hist(qpk, leaf, kept, sub_start, P, Q, span, b, height):
    """The [P, Q, span] leaf-granularity subtree histograms of the
    bottom walk, with the prefix-sum row compaction (r5): the chosen
    subtrees jointly cover ~Q/n_blocks of the leaf space, so typically
    ~1% of rows land in ANY sub-histogram — compact the relevant rows
    to a static n/8 prefix first so the per-quantile scatters scan 8x
    fewer rows."""
    n_blocks = (b**height) // span
    # The descent so far only added multiples of widths >= span, so
    # every walk's subtree start is span-ALIGNED: membership is "the
    # row's span-block == the walk's block id", the in-subtree offset
    # is just the low leaf bits, and the scatter key is the SAME for
    # every quantile — only the membership mask differs. The Q per-row
    # block ids (each < n_blocks <= 256 for the default tree) pack
    # 4-per-int32, so the per-row cost is ceil(Q/4) gathers + byte
    # compares instead of Q gathers.
    shift = span.bit_length() - 1  # span is a power of two
    mid = leaf >> shift
    lo_bits = leaf & (span - 1)
    blk = sub_start >> shift  # [P, Q] block ids

    def row_masks(qpk_r, mid_r, kept_r):
        """Per-quantile membership masks of the given rows, via the
        packed block tables."""
        masks = []
        for g in range(0, Q, 4):
            packed = jnp.zeros(P, jnp.int32)
            for j, q in enumerate(range(g, min(g + 4, Q))):
                packed |= blk[:, q] << (8 * j)
            pr = packed[qpk_r]  # ONE gather per 4 quantiles
            for j, q in enumerate(range(g, min(g + 4, Q))):
                masks.append(kept_r & (
                    mid_r == ((pr >> (8 * j)) & 0xFF)))
        return masks

    def subs_over(qpk_r, mid_r, lo_r, kept_r):
        seg = qpk_r * span + lo_r  # q-independent key
        return jnp.stack([
            jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                num_segments=P * span
                                ).reshape(P, span)
            for ok in row_masks(qpk_r, mid_r, kept_r)
        ], axis=1)  # [P, Q, span] int32

    if n_blocks <= 256 and shift <= 22:
        # Compact the relevant rows to a static n/8 prefix by
        # PREFIX-SUM scatter: each relevant row's destination is its
        # rank among relevant rows (cumsum), so two O(n) passes replace
        # the former stable argsort's ~log^2 n bitonic stages (the
        # walk's furthest-from-roofline op, r4 README). The
        # destinations are unique and monotone — the scatter coalesces;
        # irrelevant rows target index ``cap`` and drop out of bounds,
        # as do relevant rows past the cap (data concentrated enough to
        # overflow falls back to full-row scatters via lax.cond). The
        # three row fields pack into one int32 (mid <= 8 bits by the
        # n_blocks gate, lo_bits < span = 2^shift <= 2^22 by the shift
        # gate, kept 1 bit), so compaction is exactly two int32
        # scatters.
        n_rows = leaf.shape[0]
        cap = max(8192, n_rows // 8)
        rel_any = jnp.zeros(n_rows, bool)
        for ok in row_masks(qpk, mid, kept):
            rel_any |= ok
        n_rel = jnp.sum(rel_any.astype(jnp.int32))

        def compacted(_):
            # Built INSIDE the branch: cond operands are computed
            # unconditionally, so hoisting these would make the
            # overflow fallback pay for both paths.
            dest = jnp.where(
                rel_any,
                jnp.cumsum(rel_any.astype(jnp.int32)) - 1,
                cap)
            packed_row = (
                mid | (lo_bits << 8) |
                (kept.astype(jnp.int32) << (8 + shift)))
            qpk_c = jnp.zeros(cap, jnp.int32).at[dest].set(
                qpk, mode="drop")
            row_c = jnp.zeros(cap, jnp.int32).at[dest].set(
                packed_row, mode="drop")
            return subs_over(qpk_c, row_c & 0xFF,
                             (row_c >> 8) & (span - 1),
                             (row_c >> (8 + shift)).astype(bool))

        def full(_):
            return subs_over(qpk, mid, lo_bits, kept)

        return jax.lax.cond(n_rel <= cap, compacted, full, None)
    if n_blocks <= 256:
        # Exotic tree shapes whose packed row would overflow int32: no
        # compaction, full-row scatters.
        return subs_over(qpk, mid, lo_bits, kept)
    # Non-default tree shapes: block ids > 8 bits.
    return _subtree_counts(qpk, leaf, kept, sub_start, P, span)


def _mid_level_counts(mid, base, w, bucket_w, b):
    """Child counts [P, Q, b] of width-``w`` walk nodes read from the
    [P, n_mid] mid-level histogram (``w >= bucket_w``): children are
    contiguous groups of ``w/bucket_w`` buckets. The group sum runs in
    transposed layout ([groups, g, P]) — a [P, groups, g] reshape would
    leave a tiny trailing dim that TPU tiling pads ~8x. SHARED by the
    single-batch top-histogram path and the streamed top walk."""
    P, n_mid = mid.shape
    g = w // bucket_w
    lvl = mid if g == 1 else mid.T.reshape(n_mid // g, g, P).sum(1).T
    idx = base[..., None] + jnp.arange(b)  # [P, Q, b]
    return lvl[jnp.arange(P)[:, None, None], idx].astype(jnp.float32)


def _sub_level_counts(sub, sub_start, leaf_lo, w, b):
    """Child counts [P, Q, b] of width-``w`` nodes read from the
    [P, Q, span] subtree leaf histograms: children occupy w-groups
    [off + c] for c < b, where off is the node's group offset inside
    the subtree. SHARED by the single-batch sub-histogram path and the
    streamed bottom walk."""
    P, Q, span = sub.shape
    g = sub if w == 1 else sub.reshape(P, Q, span // w, w).sum(-1)
    off = (leaf_lo - sub_start) // w  # [P, Q]
    idx = off[..., None] + jnp.arange(b)  # [P, Q, b]
    return jnp.take_along_axis(g, idx, axis=2).astype(jnp.float32)


def _walk_level(noise_kind, key, scale, raw, base, level_offset, lo, hi,
                target, leaf_lo, done, b, w, pk_index=None):
    """One walk level from its raw child counts: node-id-keyed noise +
    descent step. SHARED by the single-batch walk, the owner-sharded
    walk (which passes its GLOBAL partition ids as ``pk_index``) and
    the streamed two-pass walk — the mesh/streamed/single-batch
    bit-parity guarantees rest on this being the one copy of the
    noise-keying + step arithmetic.

    At the ROOT level every quantile shares base 0, so the [P, Q, b]
    node ids are Q identical copies — and node noise is a pure function
    of (partition, node id), so the draws are too: draw once per
    (partition, child) and broadcast, skipping (Q-1)/Q of the root's
    threefry work with bit-identical values."""
    node_ids = (level_offset + base)[..., None] + jnp.arange(
        b, dtype=jnp.int32)
    if level_offset == 0:
        noise = jnp.broadcast_to(
            _node_noise(noise_kind, key, node_ids[:, :1, :], pk_index),
            node_ids.shape)
    else:
        noise = _node_noise(noise_kind, key, node_ids, pk_index)
    noisy = jnp.maximum(raw + noise * scale, 0.0)
    return _walk_step(noisy, lo, hi, target, leaf_lo, done, b, w)


def _subtree_counts(qpk, leaf, kept, sub_start, P, span, p_offset=None):
    """Leaf counts of each quantile's chosen subtree from row data:
    [P, Q, span] int32 (one masked scatter per quantile). Shared by the
    single-batch generic fallback and the streamed pass-B kernel. With
    ``p_offset`` set, ``P`` is a partition BLOCK size and only rows of
    partitions [p_offset, p_offset + P) count — the partition-block-
    chunked walk's histogram, whose per-partition content is identical
    to the full scatter's."""
    if p_offset is not None:
        rel_pk = qpk - p_offset
        in_blk = kept & (rel_pk >= 0) & (rel_pk < P)
        pk_b = jnp.clip(rel_pk, 0, P - 1)
    else:
        in_blk, pk_b = kept, qpk
    subs = []
    for q in range(sub_start.shape[1]):
        rel = leaf - sub_start[:, q][pk_b]
        ok = in_blk & (rel >= 0) & (rel < span)
        seg = pk_b * span + jnp.clip(rel, 0, span - 1)
        subs.append(jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                        num_segments=P * span
                                        ).reshape(P, span))
    return jnp.stack(subs, axis=1)


def _subtree_counts_multi(qpk, leaf, kept, sub_starts, p_offsets, Pb,
                          span, kernel_backend: str = "xla"):
    """Several tiles' subtree-leaf counts from ONE pass over the rows:
    ``sub_starts`` is [T, Pb, Qc] (each tile's walk-start leaves),
    ``p_offsets`` [T] (each tile's first global partition), output
    [T, Pb, Qc, span] int32. The multi-tile pass-B kernels call this so
    one batch recompute (bounding + leaf mapping) serves every tile the
    sweep planner packed into the round — per tile it is EXACTLY
    ``_subtree_counts`` on the same rows, so the packed result is
    bit-identical to the per-tile loop by construction.

    ``kernel_backend`` (the dp-safe knob, resolved by the caller OUTSIDE
    jit so a backend switch re-traces) selects the Pallas multi-tile
    binner — bit-identical integers (PARITY row 33) — with automatic
    XLA fallback (``kernel.fallback``) off-envelope or sans Pallas."""
    from pipelinedp_tpu.ops import kernels as hot_kernels
    binned = hot_kernels.try_hist_bin_multi(
        qpk, leaf, kept, sub_starts, p_offsets, Pb, span,
        kernel_backend)
    if binned is not None:
        return binned
    return jnp.stack([
        _subtree_counts(qpk, leaf, kept, sub_starts[t], Pb, span,
                        p_offset=p_offsets[t])
        for t in range(sub_starts.shape[0])])


def _walk_step(noisy, lo, hi, target, leaf_lo, done, b, w):
    """One level of the quantile descent: pick the child bucket whose
    cumulative noisy count crosses the rank target, re-normalize the
    target into it (``QuantileTree.compute_quantiles`` arithmetic)."""
    total = noisy.sum(-1)
    incl = jnp.cumsum(noisy, axis=-1)
    rank = target * total
    ge = incl >= rank[..., None]
    child = jnp.where(ge.any(-1), jnp.argmax(ge, -1), b - 1)
    c = jnp.take_along_axis(noisy, child[..., None], -1)[..., 0]
    cum = jnp.take_along_axis(incl, child[..., None], -1)[..., 0] - c
    width = (hi - lo) / b
    new_lo = lo + child * width
    new_target = jnp.where(
        c <= 0, 0.0,
        jnp.clip((rank - cum) / jnp.maximum(c, 1e-30), 0.0, 1.0))
    stop = done | (total <= 0)
    lo = jnp.where(stop, lo, new_lo)
    hi = jnp.where(stop, hi, new_lo + width)
    target = jnp.where(stop, target, new_target)
    leaf_lo = jnp.where(stop, leaf_lo, leaf_lo + child * w)
    return lo, hi, target, leaf_lo, stop


def _monotone_in_q(vals, quantiles):
    """Monotone in q, like the host post-processing step."""
    order = np.argsort(quantiles, kind="stable")
    mono = jax.lax.cummax(vals[:, order], axis=1)
    return mono[:, np.argsort(order)]


def _percentile_values_owned(config: FusedConfig, P_own, qrows, scale,
                             key, axis, n_dev, topo=None):
    """The quantile descent with the partition axis SHARDED over the
    mesh: each device walks only its owned block of ``P_own`` partitions
    (global partition ``axis_index * P_own + i``).

    Per level the collective protocol is: gather the owned walk bases
    (small [P, Q] int32 — every device's rows may hit any partition's
    walk), count children locally from this device's rows, then
    owner-scatter the [P, Q, b] counts so each owner receives exactly
    its block's totals — per-device ICI traffic O(P/n_dev·Q·b)
    instead of the replicated psum's O(P·Q·b). Both collectives go
    through ``parallel.sharded``'s topology-aware helpers (``topo``
    from the caller's mesh), so a hierarchical mesh keeps the scatter
    stage on ICI. Node noise is keyed by GLOBAL partition index, so
    the mesh walk is bit-identical to the single-chip walk given the
    same PRNG key."""
    # Lazy: parallel.sharded imports this module at module scope, and
    # this path only traces under a mesh sharded.py itself set up.
    from pipelinedp_tpu.parallel import sharded as psh
    qpk, leaf, kept = qrows
    b = quantile_tree_ops.DEFAULT_BRANCHING_FACTOR
    height = quantile_tree_ops.DEFAULT_TREE_HEIGHT
    quantiles = np.asarray([p / 100.0 for p in config.percentiles],
                           np.float32)
    Q = quantiles.shape[0]
    P = P_own * n_dev
    offset = jax.lax.axis_index(axis) * P_own
    pk_index = (offset + jnp.arange(P_own)).astype(jnp.uint32)

    lo = jnp.full((P_own, Q), float(config.min_value), jnp.float32)
    hi = jnp.full((P_own, Q), float(config.max_value), jnp.float32)
    target = jnp.broadcast_to(quantiles[None, :], (P_own, Q))
    leaf_lo = jnp.zeros((P_own, Q), jnp.int32)
    done = jnp.zeros((P_own, Q), bool)
    level_offset = 0
    for level in range(height):
        w = b**(height - 1 - level)
        base_own = leaf_lo // w  # [P_own, Q]
        base = psh.gather_blocks(base_own, axis, dim=0,
                                 topo=topo)  # [P, Q]
        counts = []
        for q in range(Q):
            slot = leaf // w - base[:, q][qpk]
            ok = kept & (slot >= 0) & (slot < b)
            seg = qpk * b + jnp.clip(slot, 0, b - 1)
            counts.append(
                jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                    num_segments=P * b).reshape(P, b))
        raw = psh.scatter_to_owner(jnp.stack(counts, axis=1), axis,
                                   dim=0,
                                   topo=topo).astype(jnp.float32)
        lo, hi, target, leaf_lo, done = _walk_level(
            config.noise_kind, key, scale, raw, base_own, level_offset,
            lo, hi, target, leaf_lo, done, b, w, pk_index=pk_index)
        level_offset += b**(level + 1)
    vals = lo + (hi - lo) * target  # [P_own, Q]
    return _monotone_in_q(vals, quantiles)


def _expand(mask, like):
    """Broadcasts a [N] mask against [N] or [N, D] data."""
    if like.ndim == 2:
        return mask[:, None]
    return mask


def _clip_values(config: FusedConfig, values):
    # Vectors are norm-clipped on the per-pk sum (like the reference's
    # add_noise_vector); per-partition-bound sums are clipped after the
    # segment sum. Only per-value bounds clip row-wise here.
    if (config.vector_size or config.per_partition_bounds or
            config.min_value is None):
        return values
    return jnp.clip(values, config.min_value, config.max_value)



def _release_noise_params(config: FusedConfig,
                          spec) -> dp_computations.ScalarNoiseParams:
    """The exact ScalarNoiseParams the generic combiners would build for
    this metric's spec — one noise calculus for both planes."""
    return dp_computations.ScalarNoiseParams(
        eps=spec.eps, delta=spec.delta,
        min_value=config.min_value, max_value=config.max_value,
        min_sum_per_partition=config.min_sum_per_partition,
        max_sum_per_partition=config.max_sum_per_partition,
        max_partitions_contributed=config.l0,
        max_contributions_per_partition=config.linf,
        noise_kind=config.noise_kind,
        max_contributions=config.max_contributions)


def _host_release(config: FusedConfig, specs, part, nseg,
                  rng: Optional[np.random.Generator],
                  rng_seed: Optional[int] = None, pk_index=None):
    """The scalar DP release, on host in float64: literally the
    ``dp_computations.compute_dp_*`` mechanisms the generic combiners
    call, vectorized over the pk axis. Reusing them (instead of a
    float32 device twin) keeps one release implementation for both
    planes, draws noise at full precision — float32 noise quantizes to
    a large aggregate's ULP grid — and inherits the hardened host noise
    path when ``set_secure_host_noise(True)``. ``part`` holds float64
    views of the fetched accumulator columns.

    VECTOR_SUM is the exception: its per-coordinate draws are batched
    DEVICE counter RNG (``ops/vector_noise.py``) keyed by the GLOBAL
    partition vocab index (``pk_index`` — kept indices in compact
    release, arange(P) otherwise) and the coordinate, so streamed,
    single-batch, fused and mesh releases of the same partition draw
    the same noise. ``rng_seed`` is the engine seed the vector key
    derives from; secure host noise keeps the hardened numpy path."""
    names = set(config.metrics)
    out = {}
    if "VARIANCE" in names or "MEAN" in names:
        snp = _release_noise_params(config, specs["mean_var"])
        # The device accumulated the normalized sums directly (fixed
        # point); everything here is float64.
        nsum = part["nsum"]
        if "VARIANCE" in names:
            dp_count, dp_sum, dp_mean, dp_var = (
                dp_computations.compute_dp_var(part["count"], nsum,
                                               part["nsumsq"], snp, rng))
            out["variance"] = dp_var
        else:
            dp_count, dp_sum, dp_mean = dp_computations.compute_dp_mean(
                part["count"], nsum, snp, rng)
        if "MEAN" in names:
            out["mean"] = dp_mean
        if "COUNT" in names:
            out["count"] = dp_count
        if "SUM" in names:
            out["sum"] = dp_sum
    else:
        if "COUNT" in names:
            out["count"] = dp_computations.compute_dp_count(
                part["count"], _release_noise_params(config,
                                                     specs["count"]), rng)
        if "SUM" in names:
            if config.per_partition_bounds:
                raw_sum = part["sum"]
                if config.selection is None:
                    # Public-partition parity with the generic path:
                    # every public partition receives one empty
                    # accumulator whose clipped sum is
                    # clip(0, min_sum, max_sum) (reference
                    # _add_empty_public_partitions +
                    # SumCombiner.create([])).
                    raw_sum = raw_sum + float(
                        np.clip(0.0, config.min_sum_per_partition,
                                config.max_sum_per_partition))
            else:
                # Raw sum from the normalized sum: sum(x) = sum(x - mid)
                # + count * mid, exactly, in float64.
                middle = dp_computations.compute_middle(
                    config.min_value, config.max_value)
                raw_sum = part["nsum"] + part["count"].astype(
                    np.float64) * middle
            out["sum"] = dp_computations.compute_dp_sum(
                raw_sum, _release_noise_params(config, specs["sum"]),
                rng)
    if "PRIVACY_ID_COUNT" in names:
        out["privacy_id_count"] = dp_computations.compute_dp_privacy_id_count(
            nseg, _release_noise_params(config, specs["privacy_id_count"]),
            rng)
    if "VECTOR_SUM" in names:
        spec = specs["vector_sum"]
        noise_params = dp_computations.AdditiveVectorNoiseParams(
            eps_per_coordinate=spec.eps / config.vector_size,
            delta_per_coordinate=spec.delta / config.vector_size,
            max_norm=config.vector_max_norm,
            l0_sensitivity=config.l0,
            linf_sensitivity=config.linf,
            norm_kind=config.vector_norm_kind,
            noise_kind=config.noise_kind)
        from pipelinedp_tpu.ops import noise as noise_ops
        if (noise_ops.secure_host_noise_enabled() and rng is None):
            # Hardened release: the snapping/discrete mechanisms live
            # on host — same batched call the generic combiner makes.
            out["vector_sum"] = dp_computations.add_noise_vector(
                part["vector_sum"], noise_params, rng)
        else:
            # Norm-clip on host float64 (identical to
            # add_noise_vector's clip), then batched device
            # counter-RNG draws keyed by (partition vocab index,
            # coordinate) scaled by the same calibrated per-coordinate
            # scale the numpy path uses.
            from pipelinedp_tpu.ops import vector_noise
            clipped = dp_computations._clip_vector(
                np.asarray(part["vector_sum"], dtype=np.float64),
                config.vector_max_norm, config.vector_norm_kind)
            out["vector_sum"] = vector_noise.add_vector_noise(
                clipped, noise_params, rng_seed, pk_index)
    return out


# ---------------------------------------------------------------------------
# Budget -> runtime inputs
# ---------------------------------------------------------------------------


def _noise_scales(config: FusedConfig,
                  specs: Dict[str, Any]) -> np.ndarray:
    """Device-side noise-scale inputs. Since the scalar release moved to
    the host float64 path (_host_release), the only scale the kernel
    still consumes is the percentile tree's per-level node-noise scale —
    always the LAST entry (consumed as ``noise_scales[-1]``). The budget
    is split evenly across tree levels, like the host tree
    (ops/quantile_tree.py:159-171)."""
    from pipelinedp_tpu.ops import noise as noise_ops

    if not config.percentiles:
        return np.zeros(0, dtype=np.float32)
    l0, linf = dp_computations.count_sensitivity_pair(
        config.l0, config.linf, config.max_contributions)
    spec = specs["percentile"]
    height = quantile_tree_ops.DEFAULT_TREE_HEIGHT
    eps_l = spec.eps / height
    if config.noise_kind == NoiseKind.LAPLACE:
        scale = noise_ops.laplace_scale(
            eps_l, dp_computations.compute_l1_sensitivity(l0, linf))
    else:
        scale = noise_ops.gaussian_sigma(
            eps_l, spec.delta / height,
            dp_computations.compute_l2_sensitivity(l0, linf))
    return np.asarray([scale], dtype=np.float32)


def selection_inputs(config: FusedConfig, eps: float, delta: float,
                     pre_threshold: Optional[int]):
    """(keep_table, threshold, scale, min_count) runtime inputs for the
    selection stage. Only the entries relevant to the configured strategy
    matter."""
    if config.selection is None:
        return np.zeros(2, np.float32), 0.0, 1.0, 0.0
    strategy = ps_ops.create_partition_selection_strategy(
        config.selection, eps, delta, config.selection_l0, pre_threshold)
    if isinstance(strategy, ps_ops.TruncatedGeometricPartitionStrategy):
        # probabilities() already folds in pre-thresholding; materialize
        # the effective table over [0, saturation + pre_threshold].
        size = strategy.keep_table.size + (pre_threshold or 0)
        table = strategy.probabilities(np.arange(size)).astype(np.float32)
        return table, 0.0, 1.0, 0.0
    thr = strategy.threshold
    min_count = 0.0
    if pre_threshold is not None:
        # Thresholding with pre-threshold: never keep below the
        # pre-threshold, else noisy(n - pre + 1) >= T
        # <=> noisy(n) >= T + pre - 1.
        thr = thr + pre_threshold - 1
        min_count = float(pre_threshold)
    if isinstance(strategy, ps_ops.LaplaceThresholdingPartitionStrategy):
        return np.zeros(2, np.float32), thr, strategy.noise_scale, min_count
    return np.zeros(2, np.float32), thr, strategy.noise_stddev, min_count


# ---------------------------------------------------------------------------
# Driver: budget wiring + lazy execution
# ---------------------------------------------------------------------------


def _metric_field_order(config: FusedConfig) -> List[str]:
    """MetricsTuple field order mirroring the reference compound combiner
    (VARIANCE > MEAN fold count/sum; then privacy_id_count, vector_sum)."""
    names = set(config.metrics)
    fields = []
    if "VARIANCE" in names:
        # Matches VarianceCombiner.compute_metrics dict-insertion order
        # (variance, count, sum, mean) so positional consumers see the
        # same layout on every backend.
        fields.append("variance")
        if "COUNT" in names:
            fields.append("count")
        if "SUM" in names:
            fields.append("sum")
        if "MEAN" in names:
            fields.append("mean")
    elif "MEAN" in names:
        fields.append("mean")
        if "COUNT" in names:
            fields.append("count")
        if "SUM" in names:
            fields.append("sum")
    else:
        if "COUNT" in names:
            fields.append("count")
        if "SUM" in names:
            fields.append("sum")
    if "PRIVACY_ID_COUNT" in names:
        fields.append("privacy_id_count")
    if "VECTOR_SUM" in names:
        fields.append("vector_sum")
    fields.extend(_percentile_field_names(config.percentiles))
    return fields


def request_budgets(config: FusedConfig, params: AggregateParams,
                    budget_accountant) -> Dict[str, Any]:
    """Requests exactly the budgets the reference combiner factory would
    (``combiners.py:652-721``): one mechanism per metric group, with the
    aggregation's budget weight."""
    mechanism_type = params.noise_kind.convert_to_mechanism_type()
    weight = params.budget_weight
    names = set(config.metrics)
    specs: Dict[str, Any] = {}

    def request(metric: str, internal_splits: int = 1):
        # Same split declarations as the generic factory: the release path
        # divides the granted budget evenly into this many sub-mechanisms,
        # which the PLD accountant composes individually. ``metric``
        # labels the mechanism in the privacy audit record, matching the
        # generic factory's labels.
        return budget_accountant.request_budget(
            mechanism_type, weight=weight, internal_splits=internal_splits,
            metric=metric)

    if "VARIANCE" in names:
        specs["mean_var"] = request("variance", internal_splits=3)
    elif "MEAN" in names:
        specs["mean_var"] = request("mean", internal_splits=2)
    else:
        if "COUNT" in names:
            specs["count"] = request("count")
        if "SUM" in names:
            specs["sum"] = request("sum")
    if "PRIVACY_ID_COUNT" in names:
        specs["privacy_id_count"] = request("privacy_id_count")
    if "VECTOR_SUM" in names:
        specs["vector_sum"] = request(
            "vector_sum", internal_splits=int(config.vector_size))
    if config.percentiles:
        # One budget for all percentiles, requested last — same order as
        # the generic factory (combiners.py:552-558).
        specs["percentile"] = request(
            "percentile",
            internal_splits=quantile_tree_ops.DEFAULT_TREE_HEIGHT)
    return specs


# Kept partitions fetched via the packed compact block; beyond this the
# (rare) full fetch runs instead. 8192 rows x ~10 columns x 4B = 320KB.
_COMPACT_FETCH_CAP = 8192


@instrumented_jit(phase="fetch", static_argnames=("num_partitions",
                                                  "cap"))
def _compact_fetch_kernel(keep_pk, cols, num_partitions, cap):
    """Device-side output compaction: stable-sorts kept partitions first
    (ascending pk index), gathers the first ``cap`` of every column and
    packs [meta; kept indices; columns...] into one int32 block — the
    kept count, the index map and all metric columns cross the
    high-latency host link in a single transfer."""
    keep = keep_pk[:num_partitions].astype(jnp.int32)
    order = jnp.argsort(1 - keep, stable=True)
    sel = order[:cap]
    width = sel.shape[0]
    meta = jnp.zeros(width, jnp.int32).at[0].set(jnp.sum(keep))
    gathered = [c[:num_partitions][sel] for c in cols]
    return jnp.stack([meta, sel.astype(jnp.int32)] + gathered)


def _assemble_output(config: FusedConfig, vocab, metric_arrays, rel_sel,
                     vocab_idx):
    """Released metric columns -> [(partition_key, MetricsTuple)].
    Column-wise conversion: one C-level tolist() per metric instead of a
    Python float() call per (partition, metric)."""
    fields = _metric_field_order(config)
    columns = []
    for f in fields:
        arr = metric_arrays[f]
        if arr.ndim == 1:
            columns.append(arr[rel_sel].tolist())
        else:
            columns.append(list(arr[rel_sel, :]))
    tuple_fields = tuple(fields)
    return [
        (vocab[i], _create_named_tuple_instance(
            "MetricsTuple", tuple_fields, vals))
        for i, vals in zip(vocab_idx.tolist(), zip(*columns))
    ]


def _record_selection_audit(strategy, pre: int, post: int,
                            path: str) -> None:
    """The selection-seam audit counters: pre/post-selection partition
    counts + one structured event per selection, feeding the run
    report's ``privacy.partition_selection`` section. Gated on the
    audit knob (``PIPELINEDP_TPU_AUDIT``); pure host-side bookkeeping —
    DP outputs are bit-identical on or off."""
    from pipelinedp_tpu import obs
    if not obs.audit.audit_enabled():
        return
    obs.inc("selection.partitions_pre", int(pre))
    obs.inc("selection.partitions_post", int(post))
    obs.event("selection.applied", strategy=str(strategy.value),
              pre=int(pre), post=int(post), path=path)


def _audit_expected_errors(config: FusedConfig, specs, metric_arrays,
                           rel_sel) -> None:
    """Per-metric expected relative error into the audit registry: the
    calibrated noise stddev (where the standard predictors apply)
    against the mean |released aggregate| — the machine-readable twin of
    the utility-analysis engine's ``error_expected``, captured at the
    release seam where both sides are known. Never raises."""
    from pipelinedp_tpu import obs
    if not obs.audit.audit_enabled():
        return
    try:
        names = set(config.metrics)
        stds: Dict[str, float] = {}
        if "VARIANCE" in names or "MEAN" in names:
            # The combiner splits the granted budget evenly into its
            # count / normalized-sum (/ sum-of-squares) sub-mechanisms;
            # predict the count leg's noise at that per-sub share.
            spec = specs["mean_var"]
            k = 3 if "VARIANCE" in names else 2
            sub = dataclasses.replace(
                _release_noise_params(config, spec),
                eps=spec.eps / k, delta=(spec.delta or 0.0) / k)
            stds["count"] = dp_computations.compute_dp_count_noise_std(sub)
        else:
            if "COUNT" in names:
                stds["count"] = dp_computations.compute_dp_count_noise_std(
                    _release_noise_params(config, specs["count"]))
            if "SUM" in names:
                stds["sum"] = dp_computations.compute_dp_sum_noise_std(
                    _release_noise_params(config, specs["sum"]))
        if "PRIVACY_ID_COUNT" in names:
            snp = _release_noise_params(config,
                                        specs["privacy_id_count"])
            l0, linf = snp.pid_count_sensitivities()
            stds["privacy_id_count"] = dp_computations._noise_std(
                snp.eps, snp.delta, l0, linf, snp.noise_kind)
        for field in _metric_field_order(config):
            arr = metric_arrays.get(field)
            if arr is None:
                continue
            arr = np.asarray(arr)
            if arr.ndim != 1:
                continue  # vector metrics: no scalar scale
            released = arr[rel_sel] if len(rel_sel) else arr[:0]
            scale = (float(np.mean(np.abs(released)))
                     if released.size else None)
            std = stds.get(field)
            rec = {"metric": field, "noise_stddev": std,
                   "aggregate_scale": scale,
                   "partitions": int(released.size)}
            if std is not None and scale:
                rec["expected_relative_error"] = float(std / scale)
            obs.audit.record_metric_error(rec)
    except Exception:
        pass  # an error estimate must never take the release down


def _maybe_append_run_ledger(name: str = "engine.aggregate",
                             mesh=None) -> None:
    """Traced engine runs persist their run report into the durable
    ledger store (when a store directory resolves — see
    ``obs.store.ledger_dir``): the per-request audit record that
    otherwise dies with the process. Each append carries only this
    request's delta; ``mesh`` keys the fingerprint on the mesh shape
    the request actually ran on."""
    from pipelinedp_tpu import obs
    if not obs.trace_enabled():
        return
    obs.store.maybe_append_run_report(name, mesh=mesh)


def fused_fx_bits(config: FusedConfig, padded_rows: int) -> int:
    """The fixed-point lane width for a fused bucket, sized from the
    bucket's PADDED row edge — an upper bound on every member's real
    rows, so the batched kernel's static capacity guard holds for the
    whole bucket. A solo request sizes from its real row count instead
    and may pick wider lanes; both encodings are exact integer
    decompositions of the same quantized per-row values, so the folded
    float64 release is bit-identical either way (the lane plan is a
    capacity choice, never a precision choice)."""
    if _fixedpoint_layout(config) or _vector_fx(config):
        return _fx_plan(max(int(padded_rows), 1))[0]
    return 12


@dataclasses.dataclass
class FusionPrep:
    """One request's host-side preparation for a fused batch: exactly
    the runtime inputs a solo dispatch would feed the kernel, before
    any bucket padding. Built only by ``LazyFusedResult.prepare_fused``
    (after ``compute_budgets()``); consumed by the serve-fusion layer
    (``serve/fusion.py``), which pads members to the bucket edge and
    stacks them along the leading request axis."""
    lazy: "LazyFusedResult"
    encoded: EncodedData
    P: int
    P_pad: int
    scales: np.ndarray
    keep_table: np.ndarray
    thr: float
    s_scale: float
    min_count: float
    rows_per_uid: float
    key: Any

    def stack_signature(self) -> Tuple:
        """Aux-input shapes that must agree for requests to stack:
        bucketing already fixed (rows, partitions, fx_bits), but the
        selection keep-table length varies with the request's
        (eps, delta) and the scales vector with the metric set — the
        executor sub-groups a bucket's batch on this, so a mismatch
        splits the batch instead of failing it."""
        return (self.scales.shape, self.keep_table.shape,
                int(np.asarray(self.encoded.values).ndim))


class LazyFusedResult:
    """Iterable of (partition_key, MetricsTuple); runs the fused kernel on
    first iteration — after ``compute_budgets()``, honoring the two-phase
    protocol. Iterating again reuses the cached result."""

    def __init__(self, rows, params: AggregateParams, config: FusedConfig,
                 data_extractors, public_partitions, specs,
                 selection_spec, rng_seed: Optional[int] = None,
                 mesh=None, checkpoint=None, ingest_executor=None,
                 stream_cache=None):
        self._ingest_executor = ingest_executor
        self._stream_cache = stream_cache
        self._rows = rows
        self._params = params
        self._config = config
        self._extractors = data_extractors
        self._public = public_partitions
        self._specs = specs
        self._selection_spec = selection_spec
        self._rng_seed = rng_seed
        self._mesh = mesh
        self._checkpoint = checkpoint
        self._cache = None
        #: Serve-fusion seam: an EncodedData a fusion offer already
        #: built for exactly these rows/extractors — _execute consumes
        #: it instead of re-encoding, so a fused request that falls
        #: back to solo execution (singleton window, unfusable prep)
        #: never pays the O(rows) host encode twice.
        self._encoded_hint: Optional[EncodedData] = None
        #: host/device timing split of the last _execute, for bench.py.
        self.timings: Optional[Dict[str, float]] = None

    def __iter__(self):
        # Generator function: the body (and thus execution) is deferred
        # until the first next() — downstream generator expressions call
        # iter() at construction time, which must not trigger the kernel
        # before compute_budgets().
        if self._cache is None:
            self._cache = self._execute()
        yield from self._cache

    def rebind_rows(self, rows) -> None:
        """Sketch-first seam (``sketch/engine.py``): phase 2 of the
        two-phase unbounded-key path replaces the full input with the
        candidate-filtered rows before first iteration — budgets were
        registered against the ORIGINAL graph build, which is exactly
        the two-phase protocol's contract (specs are lazy; only the
        rows narrow). Refuses after execution: the cache would already
        embody the old rows."""
        if self._cache is not None:
            raise RuntimeError(
                "cannot rebind rows after the fused result executed")
        self._rows = rows
        self._encoded_hint = None

    def _execute(self):
        from pipelinedp_tpu import obs

        config = self._config
        params = self._params
        # Span-derived timing: the host_encode_s / device_s /
        # host_decode_s fields keep their names and semantics; they are
        # now views over the run tracer's "engine.*" span totals.
        tr = obs.run_tracer()
        with tr.span("engine.encode", cat="engine"):
            encoded = (self._encoded_hint if self._encoded_hint
                       is not None else
                       encode(self._rows, self._extractors,
                              config.vector_size, self._public,
                              require_pid=not
                              config.bounds_already_enforced))
        self.timings = {"host_encode_s": tr.total("engine.encode"),
                        "device_s": 0.0, "host_decode_s": 0.0}
        P = len(encoded.pk_vocab)
        if P == 0:
            return []
        scales = _noise_scales(config, self._specs)
        # Without privacy ids the selection user-count estimate divides by
        # the max rows one user may own (reference dp_engine.py:163-169).
        if config.bounds_already_enforced:
            rows_per_uid = float(params.max_contributions or
                                 params.max_contributions_per_partition)
        else:
            rows_per_uid = 1.0
        if self._selection_spec is not None:
            keep_table, thr, s_scale, min_count = selection_inputs(
                config, self._selection_spec.eps,
                self._selection_spec.delta, params.pre_threshold)
        else:
            keep_table, thr, s_scale, min_count = selection_inputs(
                config, 1.0, 1e-9, None)

        from pipelinedp_tpu import streaming
        if streaming.should_stream(config, encoded.n_rows, self._mesh):
            # Multi-batch ingest: the dataset exceeds one device batch.
            # Partials accumulate on host (int64 / folded float64),
            # selection runs once on device, release below as usual.
            with tr.span("engine.device", cat="engine",
                         path="streamed"):
                keep_np, part64, stream_stats = (
                    streaming.stream_partials_and_select(
                        config, encoded, scales, keep_table, thr,
                        s_scale, min_count, rows_per_uid,
                        self._rng_seed, mesh=self._mesh,
                        checkpoint=self._checkpoint,
                        executor=self._ingest_executor,
                        cache_bytes=self._stream_cache))
            self.timings["device_s"] = tr.total("engine.device")
            self.timings["stream_batches"] = stream_stats["n_batches"]
            if "resumed_from_batch" in stream_stats:
                self.timings["stream_resumed_from"] = (
                    stream_stats["resumed_from_batch"])
                self.timings["stream_checkpoint_saves"] = (
                    stream_stats["checkpoint_saves"])
            # Elastic recovery trail: reshard count + history reach the
            # run report/bench record, so a run that survived a device
            # loss says so (and at which chunk) instead of
            # masquerading as an uneventful capture.
            if stream_stats.get("mesh_reshards"):
                self.timings["stream_mesh_reshards"] = (
                    stream_stats["mesh_reshards"])
                self.timings["stream_reshard_history"] = (
                    stream_stats["reshard_history"])
            # Transfer/compute split: staging+enqueue wall vs the time
            # blocked waiting for kernel results (the overlap evidence).
            self.timings["stream_stage_s"] = stream_stats["stage_s"]
            self.timings["stream_fold_wait_s"] = stream_stats["fold_wait_s"]
            # Per-phase pass-A breakdown from the ingest executor: busy
            # time per phase vs the loop wall clock; overlap_frac > 0
            # means phase time was hidden inside the wall.
            for k in ("t_stage", "t_fold", "t_device", "t_total",
                      "overlap_frac", "executor"):
                self.timings[f"stream_{k}"] = stream_stats[k]
            if "pass_b_source" in stream_stats:
                self.timings["stream_pass_b"] = stream_stats["pass_b_source"]
                self.timings["stream_pass_b_rounds"] = (
                    stream_stats["pass_b_rounds"])
                # Sweep-planner evidence: how many stream traversals
                # pass B actually paid for how many (quantile-group x
                # partition-block) tiles, and the bytes re-shipped over
                # the host link past the device cache's prefix.
                for k in ("pass_b_sweeps", "pass_b_tiles",
                          "pass_b_tiles_per_sweep",
                          "pass_b_cached_batches",
                          "pass_b_reshipped_bytes",
                          "pass_b_sweep_s"):
                    self.timings[f"stream_{k}"] = stream_stats[k]
            with tr.span("engine.release", cat="engine"):
                part64 = {k: v[:P] for k, v in part64.items()}
                if self._public is not None:
                    rel_sel = vocab_idx = np.arange(P)
                else:
                    # Release ONLY the kept partitions, in ascending pk
                    # order — the same host-noise draw sequence as the
                    # single-batch compact fetch path, so a streamed
                    # run and a single-batch run with the same seed
                    # release bit-identical scalar values whenever
                    # their kept sets and accumulators agree.
                    kept_idx = np.flatnonzero(keep_np[:P])
                    part64 = {k: v[kept_idx]
                              for k, v in part64.items()}
                    rel_sel = np.arange(len(kept_idx))
                    vocab_idx = kept_idx
                # lint: disable=rng-purity(host-release rng seeded by the engine seed)
                rng = (np.random.default_rng(self._rng_seed)
                       if self._rng_seed is not None else None)
                metric_arrays = _host_release(
                    config, self._specs, part64,
                    part64["privacy_id_count_raw"], rng,
                    rng_seed=self._rng_seed, pk_index=vocab_idx)
                for qi, name in enumerate(
                        _percentile_field_names(config.percentiles)):
                    vals_q = stream_stats["percentile_values"][:P, qi]
                    metric_arrays[name] = (
                        vals_q if self._public is not None
                        else vals_q[vocab_idx])
                out = _assemble_output(config, encoded.pk_vocab,
                                       metric_arrays, rel_sel,
                                       vocab_idx)
            self.timings["host_decode_s"] = tr.total("engine.release")
            _audit_expected_errors(config, self._specs, metric_arrays,
                                   rel_sel)
            _maybe_append_run_ledger(mesh=self._mesh)
            return out

        # The execution planner's resolution for THIS single-batch
        # request (streamed requests resolve inside
        # stream_partials_and_select): the plan.applied events and the
        # run report's plan section exist for every request, and the
        # walk's mid-request cap read (knob_value at jit-trace time)
        # buckets at this request's shape instead of a stale previous
        # request's.
        from pipelinedp_tpu import plan as _plan_mod
        _plan_mod.resolve(
            shape={"rows": int(encoded.n_rows), "partitions": int(P),
                   "quantiles": len(config.percentiles or ())},
            mesh=self._mesh)
        with tr.span("engine.device", cat="engine", path="single_batch"):
            keep_pk, raw, fx_bits = _run_fused_kernel(
                config, encoded, scales, keep_table, thr, s_scale,
                min_count, rows_per_uid, self._rng_seed, self._mesh)

            # Fetching the outputs forces device execution; the fetch
            # is attributed to device_s, the float64 release + row
            # assembly to decode_s. All rank-1 outputs ride ONE stacked
            # transfer — the tunneled host<->device link pays per round
            # trip, not per byte here. The stack is int32 with float
            # columns BITCAST into it: integer lanes move bit-exactly,
            # whereas small ints bitcast to float32 become subnormals
            # that TPUs flush to zero (and a float32 CAST would corrupt
            # counts above 2^24).
            flat = sorted(k for k, v in raw.items() if v.ndim == 1)
            cols = []
            for name in flat:
                arr = raw[name]
                cols.append(arr if arr.dtype == jnp.int32 else
                            jax.lax.bitcast_convert_type(
                                arr.astype(jnp.float32), jnp.int32))
            # With private selection most partitions are usually
            # dropped: compact ON DEVICE and fetch kept count + kept
            # indices + kept columns as ONE packed block — a single
            # transfer over the high-latency link instead of a full
            # [K, P] fetch plus extra round trips. Partitions kept
            # beyond the static cap (rare: a huge keyspace where
            # selection keeps >8192 keys) fall back to the full fetch.
            compact = self._public is None
            if compact:
                cap = min(P, _COMPACT_FETCH_CAP)
                packed = np.asarray(_compact_fetch_kernel(
                    keep_pk, tuple(cols), P, cap))
                n_keep = int(packed[0, 0])
                if n_keep > cap:  # fallback: fetch everything
                    stacked = np.asarray(
                        jnp.stack([keep_pk.astype(jnp.int32)] +
                                  cols))[:, :P]
                    kept_idx = np.flatnonzero(stacked[0] > 0)
                    n_rel = P
                    compact = False
                else:
                    stacked = packed[1:, :n_keep]
                    kept_idx = stacked[0]
                    n_rel = n_keep  # release only kept rows
                    kept_order = jnp.asarray(kept_idx)  # rank-2 gathers
            else:
                stacked = np.asarray(
                    jnp.stack([keep_pk.astype(jnp.int32)] +
                              cols))[:, :P]
                kept_idx = np.flatnonzero(stacked[0] > 0)
                n_rel = P  # release all rows, select kept at the end
            fetched = {}
            for i, name in enumerate(flat):
                col = stacked[1 + i]
                fetched[name] = (col if raw[name].dtype == jnp.int32
                                 else col.view(np.float32))
            for name, arr in raw.items():  # rank-2 (vector) outputs
                if arr.ndim != 1:
                    if compact:
                        fetched[name] = np.asarray(arr[kept_order])
                    else:
                        fetched[name] = np.asarray(arr)[:P]
        self.timings["device_s"] = tr.total("engine.device")
        if config.selection is not None:
            # The selection seam: every vocab entry is a populated
            # partition, so P is the pre-selection count and the kept
            # index set is the post-selection count.
            _record_selection_audit(config.selection, P, len(kept_idx),
                                    "single_batch")

        # Only materialize kept partitions (with private selection
        # the kept fraction can be tiny — never walk the full pk
        # axis in Python). In compact mode the released arrays
        # already hold only kept rows.
        if self._public is not None:
            rel_sel = vocab_idx = np.arange(P)
        elif compact:
            rel_sel = np.arange(n_rel)
            vocab_idx = kept_idx
        else:
            rel_sel = vocab_idx = kept_idx
        return self._finish_release(encoded, P, fetched, fx_bits,
                                    rel_sel, vocab_idx)

    def _finish_release(self, encoded: EncodedData, P: int, fetched,
                        fx_bits: int, rel_sel, vocab_idx):
        """The scalar DP release tail, in float64 via the shared
        mechanisms — ONE implementation for the solo single-batch path
        and the serve-fusion batch path (bit-identity between them is
        the PARITY row 35 contract, so they must share this code, not
        mirror it). Integer columns stay integral: the hardened noise
        path dispatches on dtype (discrete Laplace for counts — no
        float noise bits), exactly like the generic combiners' int
        accumulators. ``fetched`` holds host copies of the device
        outputs already restricted to the rows ``rel_sel`` releases
        (kept rows in compact mode, the full [:P] axis otherwise)."""
        from pipelinedp_tpu import obs

        config = self._config
        tr = obs.run_tracer()
        with tr.span("engine.release", cat="engine"):
            part64 = {
                k: (v.astype(np.int64) if v.dtype.kind in "iu" else
                    v.astype(np.float64)) for k, v in fetched.items()
            }
            # Reassemble fixed-point value lanes into float64 columns.
            _fold_fixedpoint(config, part64, fx_bits)
            # lint: disable=rng-purity(host-release rng seeded by the engine seed)
            rng = (np.random.default_rng(self._rng_seed)
                   if self._rng_seed is not None else None)
            # Row-aligned global vocab indices for the vector noise
            # counters: ``vocab_idx`` when the release rows ARE the
            # kept set (compact / public), arange otherwise (the
            # full-fetch fallback releases every vocab row in order).
            n_rel_rows = len(part64["count"])
            row_vocab = (np.asarray(vocab_idx)
                         if len(vocab_idx) == n_rel_rows
                         else np.arange(n_rel_rows))
            metric_arrays = _host_release(config, self._specs, part64,
                                          part64["privacy_id_count_raw"],
                                          rng, rng_seed=self._rng_seed,
                                          pk_index=row_vocab)
            for name in _percentile_field_names(config.percentiles):
                metric_arrays[name] = fetched[name]
            out = _assemble_output(config, encoded.pk_vocab,
                                   metric_arrays, rel_sel, vocab_idx)
        if self.timings is not None:
            self.timings["host_decode_s"] = tr.total("engine.release")
        _audit_expected_errors(config, self._specs, metric_arrays, rel_sel)
        _maybe_append_run_ledger(mesh=self._mesh)
        return out

    # --- serve-fusion seams (phase 1 / phase 2 of a fused execution) ---

    def prepare_fused(self, encoded: Optional[EncodedData] = None
                      ) -> Optional["FusionPrep"]:
        """Serve-fusion seam, phase 1: the host-side preparation a solo
        ``_execute`` would do before device dispatch — encode, noise
        scales, selection inputs, the per-request PRNG key — WITHOUT
        dispatching. Must run after ``compute_budgets()`` (the two-phase
        protocol), exactly like iteration. Returns None when this
        request cannot join a fused batch (sharded backend, streamed
        scale, empty vocabulary): the fusion layer then falls back to
        solo execution, visibly."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.ops import noise as noise_ops

        config = self._config
        if self._mesh is not None:
            return None
        tr = obs.run_tracer()
        with tr.span("engine.encode", cat="engine"):
            if encoded is None:
                encoded = encode(
                    self._rows, self._extractors, config.vector_size,
                    self._public,
                    require_pid=not config.bounds_already_enforced)
        P = len(encoded.pk_vocab)
        if P == 0:
            return None
        from pipelinedp_tpu import streaming
        if streaming.should_stream(config, encoded.n_rows, self._mesh):
            return None
        self.timings = {"host_encode_s": tr.total("engine.encode"),
                        "device_s": 0.0, "host_decode_s": 0.0,
                        "fused": True}
        scales = _noise_scales(config, self._specs)
        if config.bounds_already_enforced:
            rows_per_uid = float(
                self._params.max_contributions or
                self._params.max_contributions_per_partition)
        else:
            rows_per_uid = 1.0
        if self._selection_spec is not None:
            keep_table, thr, s_scale, min_count = selection_inputs(
                config, self._selection_spec.eps,
                self._selection_spec.delta, self._params.pre_threshold)
        else:
            keep_table, thr, s_scale, min_count = selection_inputs(
                config, 1.0, 1e-9, None)
        seed = (self._rng_seed if self._rng_seed is not None else
                int(noise_ops._host_rng.integers(0, 2**31 - 1)))
        # lint: disable=rng-purity(seed protocol root key, pure in rng_seed)
        key = jax.random.PRNGKey(seed)
        return FusionPrep(
            lazy=self, encoded=encoded, P=P, P_pad=_pad_pow2(P),
            scales=np.asarray(scales), keep_table=np.asarray(keep_table),
            thr=float(thr), s_scale=float(s_scale),
            min_count=float(min_count), rows_per_uid=float(rows_per_uid),
            key=key)

    def finish_from_fused(self, prep: "FusionPrep", keep_np, raw_np,
                          fx_bits: int):
        """Serve-fusion seam, phase 2: finish THIS request from its
        slice of the batched kernel's outputs. Replicates the solo
        fetch decisions — the compact-vs-full release choice changes
        which rows consume a seeded host rng's draws, so it is part of
        the bit-identity contract — then runs the shared release tail
        and installs the result as the lazy cache (iteration returns
        it without dispatching a solo program)."""
        config = self._config
        P = prep.P
        keep = np.asarray(keep_np)[:P]
        kept_idx = np.flatnonzero(keep > 0)
        if self._public is not None:
            fetched = {k: np.asarray(v)[:P] for k, v in raw_np.items()}
            rel_sel = vocab_idx = np.arange(P)
        elif len(kept_idx) <= min(P, _COMPACT_FETCH_CAP):
            # The solo path's packed compact fetch: release ONLY the
            # kept rows, ascending pk order.
            fetched = {k: np.asarray(v)[:P][kept_idx]
                       for k, v in raw_np.items()}
            rel_sel = np.arange(len(kept_idx))
            vocab_idx = kept_idx
        else:
            fetched = {k: np.asarray(v)[:P] for k, v in raw_np.items()}
            rel_sel = vocab_idx = kept_idx
        if config.selection is not None:
            _record_selection_audit(config.selection, P, len(kept_idx),
                                    "fused_batch")
        out = self._finish_release(prep.encoded, P, fetched, fx_bits,
                                   rel_sel, vocab_idx)
        self._cache = out
        return out


def _run_fused_kernel(config: FusedConfig, encoded: EncodedData, scales,
                      keep_table, thr, s_scale, min_count, rows_per_uid,
                      rng_seed, mesh):
    """Shared encode→seed→dispatch scaffolding of the lazy results: one
    place owns the kernel/sharded invocation and the seed protocol."""
    from pipelinedp_tpu.ops import noise as noise_ops

    P = len(encoded.pk_vocab)
    P_pad = _pad_pow2(P)
    seed = (rng_seed if rng_seed is not None else
            int(noise_ops._host_rng.integers(0, 2**31 - 1)))
    # lint: disable=rng-purity(seed protocol root key, pure in rng_seed)
    key = jax.random.PRNGKey(seed)
    # Lane plan from the GLOBAL row count (the mesh's cross-device psum
    # adds per-shard lane sums, so capacity is a global bound; padding
    # rows are masked to zero and never consume capacity); the same value
    # drives the host-side lane fold. Pipelines with no fixed-point value
    # columns (COUNT/PRIVACY_ID_COUNT-only, PERCENTILE, VECTOR_SUM,
    # select_partitions) skip the plan entirely — their int32 count
    # columns are exact to 2^31 rows and must not inherit the lane cap.
    # VECTOR_SUM joins the plan when its accumulator is 'fx' (the
    # coordinate lanes share the scalar capacity arithmetic).
    if _fixedpoint_layout(config) or _vector_fx(config):
        fx_bits, _ = _fx_plan(max(encoded.n_rows, 1))
    else:
        fx_bits = 12
    # The kernel-backend knob resolves HERE, outside jit, and rides in
    # as a static argument: jit caches by signature, so an env/seam/
    # plan switch between calls re-traces instead of silently reusing
    # the other backend's program (and the cost observatory's table
    # keys the two signatures apart for before/after verdicts).
    from pipelinedp_tpu import plan as plan_mod
    kernel_backend = str(plan_mod.knob_value("kernel_backend"))
    from pipelinedp_tpu import obs
    if kernel_backend == "pallas" and config.percentiles:
        # The single-batch quantile walk builds its subtree counts
        # through the compacted/block-chunked ``_subtree_counts``
        # paths, which have no Pallas twin (only streamed pass B's
        # multi-tile binner does) — say so, out loud: a requested
        # backend silently not running is the one thing the knob must
        # never do. The fused per-pk reduction in this same program
        # still dispatches Pallas.
        obs.inc("kernel.fallbacks")
        obs.event("kernel.fallback", site="walk_subtree_counts",
                  reason="single_batch_walk",
                  percentiles=len(config.percentiles))
    if mesh is not None:
        from pipelinedp_tpu.parallel import sharded_fused_aggregate
        with obs.device_annotation("pdp.sharded_fused_aggregate"):
            keep_pk, raw = sharded_fused_aggregate(
                mesh, config, P_pad, encoded.pid, encoded.pk,
                encoded.values if config.needs_values else None,
                np.ones(encoded.n_rows, bool), scales, keep_table, thr,
                s_scale, min_count, rows_per_uid, key, fx_bits,
                kernel_backend=kernel_backend)
        return keep_pk, raw, fx_bits
    pid, pk, values, valid = pad_and_put(encoded, config.vector_size,
                                         with_values=config.needs_values)
    with obs.device_annotation("pdp.fused_aggregate"):
        keep_pk, raw = fused_aggregate_kernel(
            config, P_pad, pid, pk, values, valid, jnp.asarray(scales),
            jnp.asarray(keep_table), jnp.float32(thr),
            jnp.float32(s_scale), jnp.float32(min_count),
            jnp.float32(rows_per_uid), key, fx_bits=fx_bits,
            kernel_backend=kernel_backend)
    return keep_pk, raw, fx_bits


class LazySelectResult:
    """Iterable of kept partition keys; runs the fused kernel (with an
    empty metric set — only bounding + selection) on first iteration."""

    def __init__(self, rows, params, data_extractors, spec, rng_seed,
                 mesh):
        self._rows = rows
        self._params = params
        self._extractors = data_extractors
        self._spec = spec
        self._rng_seed = rng_seed
        self._mesh = mesh
        self._cache = None

    def __iter__(self):
        if self._cache is None:
            self._cache = self._execute()
        yield from self._cache

    def _execute(self):
        params = self._params
        config = FusedConfig(
            metrics=(), noise_kind=NoiseKind.LAPLACE, linf=None,
            l0=params.max_partitions_contributed,
            per_partition_bounds=False, min_value=None, max_value=None,
            min_sum_per_partition=None, max_sum_per_partition=None,
            vector_size=None, vector_norm_kind=None, vector_max_norm=None,
            selection=params.partition_selection_strategy,
            bounds_already_enforced=False)
        encoded = encode(self._rows, self._extractors, None, None)
        P = len(encoded.pk_vocab)
        if P == 0:
            return []
        keep_table, thr, s_scale, min_count = selection_inputs(
            config, self._spec.eps, self._spec.delta, params.pre_threshold)
        from pipelinedp_tpu import streaming
        if streaming.should_stream(config, encoded.n_rows, self._mesh):
            keep_np, _, _ = streaming.stream_partials_and_select(
                config, encoded, np.zeros(1, np.float32), keep_table,
                thr, s_scale, min_count, 1.0, self._rng_seed,
                mesh=self._mesh)
            vocab = encoded.pk_vocab
            out = [vocab[i] for i in np.flatnonzero(keep_np[:P])]
            _maybe_append_run_ledger("engine.select_partitions",
                                     mesh=self._mesh)
            return out
        keep_pk, _, _ = _run_fused_kernel(
            config, encoded, np.zeros(0, np.float32), keep_table, thr,
            s_scale, min_count, 1.0, self._rng_seed, self._mesh)
        vocab = encoded.pk_vocab
        # Same packed compact fetch as the aggregation path: kept count
        # + kept indices in one small transfer instead of the full
        # [P] keep vector (selection typically keeps a tiny fraction).
        cap = min(P, _COMPACT_FETCH_CAP)
        packed = np.asarray(_compact_fetch_kernel(keep_pk, (), P, cap))
        n_keep = int(packed[0, 0])
        if n_keep > cap:
            keep_np = np.asarray(keep_pk)[:P]
            out = [vocab[i] for i in np.flatnonzero(keep_np)]
        else:
            out = [vocab[i] for i in packed[1, :n_keep].tolist()]
        _record_selection_audit(config.selection, P, len(out),
                                "select_partitions")
        _maybe_append_run_ledger("engine.select_partitions",
                                 mesh=self._mesh)
        return out


def build_fused_select_partitions(col, params, data_extractors,
                                  budget_accountant, report_gen,
                                  rng_seed=None,
                                  mesh=None) -> LazySelectResult:
    """Fused ``select_partitions`` (reference ``dp_engine.py:204-278``):
    the L0 bound over distinct (pid, pk) pairs and the batched selection
    are exactly the aggregation kernel with no metrics requested."""
    from pipelinedp_tpu.aggregate_params import MechanismType

    spec = budget_accountant.request_budget(
        mechanism_type=MechanismType.GENERIC, metric="partition_selection")
    strategy = params.partition_selection_strategy
    report_gen.add_stage(
        f"Cross-partition contribution bounding: for each privacy_id "
        f"randomly select max(actual_partition_contributed, "
        f"{params.max_partitions_contributed}) partitions (fused on "
        "device).")
    report_gen.add_stage(
        lambda: f"Private Partition selection: using {strategy.value} "
        f"method with (eps={spec.eps}, delta={spec.delta}) — batched over "
        "all partitions")
    return LazySelectResult(col, params, data_extractors, spec, rng_seed,
                            mesh)


def build_fused_aggregation(col, params: AggregateParams, data_extractors,
                            public_partitions, budget_accountant,
                            report_gen, rng_seed=None,
                            mesh=None, checkpoint=None,
                            ingest_executor=None,
                            stream_cache=None) -> LazyFusedResult:
    """Engine entry point for the fused plane: requests budgets (same
    pattern as the generic path, so the privacy semantics are identical),
    registers report stages, returns the lazy result."""
    from pipelinedp_tpu.aggregate_params import MechanismType

    public = public_partitions is not None
    config = FusedConfig.from_params(params, public)
    specs = request_budgets(config, params, budget_accountant)
    selection_spec = None
    if not public:
        selection_spec = budget_accountant.request_budget(
            mechanism_type=MechanismType.GENERIC,
            metric="partition_selection")

    if not config.bounds_already_enforced:
        if config.max_contributions is not None:
            report_gen.add_stage(
                f"User contribution bounding: randomly selected not more "
                f"than {config.max_contributions} contributions (fused on "
                "device).")
        else:
            report_gen.add_stage(
                f"Per-partition contribution bounding: for each privacy_id "
                f"and each partition, randomly select "
                f"max(actual_contributions_per_partition, {config.linf}) "
                f"contributions (fused on device).")
            report_gen.add_stage(
                f"Cross-partition contribution bounding: for each "
                f"privacy_id randomly select "
                f"max(actual_partition_contributed, {config.l0}) "
                "partitions (fused on device).")
    if public:
        report_gen.add_stage(
            "Public partition selection: dropped non public partitions; "
            "missing public partitions added as empty (dense pk axis).")
    else:
        strategy = params.partition_selection_strategy
        report_gen.add_stage(
            lambda: f"Private Partition selection: using {strategy.value} "
            f"method with (eps={selection_spec.eps}, "
            f"delta={selection_spec.delta}) — batched over all partitions")
    report_gen.add_stage(
        lambda: "Computed metrics "
        f"{sorted(set(m.lower() for m in config.metrics))} in one fused "
        "XLA program")
    return LazyFusedResult(col, params, config, data_extractors,
                           public_partitions, specs, selection_spec,
                           rng_seed=rng_seed, mesh=mesh,
                           checkpoint=checkpoint,
                           ingest_executor=ingest_executor,
                           stream_cache=stream_cache)
