"""Contribution bounding — caps each privacy unit's influence by sampling
(capability parity with the reference's
``pipeline_dp/contribution_bounders.py``; strategies at :56, :108, :153).

Expressed over abstract backend ops so every backend (host generators or the
JAX array plane) executes the same logical graph; the fused TPU path
implements the same semantics directly as per-segment top-k sampling (see
``ops.segment``/``jax_engine``).
"""

from __future__ import annotations

import abc
import collections
from typing import Callable, Iterable

from pipelinedp_tpu import sampling_utils


class ContributionBounder(abc.ABC):
    """Interface for contribution bounding (reference :25-53). Also fuses
    the per-(privacy_id, partition_key) aggregation via ``aggregate_fn``
    (= ``combiner.create_accumulator``)."""

    @abc.abstractmethod
    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn: Callable):
        """Input elements: (privacy_id, partition_key, value). Output:
        ((privacy_id, partition_key), accumulator)."""


class SamplingCrossAndPerPartitionContributionBounder(ContributionBounder):
    """The default strategy (reference :56-105): linf cap by sampling per
    (pid, pk), then L0 cap by sampling partitions per pid."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        max_partitions = params.max_partitions_contributed
        max_per_partition = params.max_contributions_per_partition
        col = backend.map_tuple(
            col, lambda pid, pk, v: ((pid, pk), v),
            "Rekey to ((privacy_id, partition_key), value)")
        col = backend.sample_fixed_per_key(
            col, max_per_partition, "Sample per (privacy_id, partition_key)")
        report_generator.add_stage(
            f"Per-partition contribution bounding: for each privacy_id and "
            f"each partition, randomly select "
            f"max(actual_contributions_per_partition, {max_per_partition}) "
            f"contributions.")
        col = backend.map_values(
            col, aggregate_fn,
            "Apply aggregate_fn after per-partition bounding")
        # ((pid, pk), accumulator)
        col = backend.map_tuple(
            col, lambda pid_pk, acc: (pid_pk[0], (pid_pk[1], acc)),
            "Rekey to (privacy_id, (partition_key, accumulator))")
        col = backend.sample_fixed_per_key(col, max_partitions,
                                           "Sample per privacy_id")
        report_generator.add_stage(
            f"Cross-partition contribution bounding: for each privacy_id "
            f"randomly select max(actual_partition_contributed, "
            f"{max_partitions}) partitions")

        def unnest(pid_and_pk_accs):
            pid, pk_accs = pid_and_pk_accs
            return (((pid, pk), acc) for pk, acc in pk_accs)

        return backend.flat_map(col, unnest,
                                "Rekey by privacy_id and unnest")


class SamplingPerPrivacyIdContributionBounder(ContributionBounder):
    """Caps the *total* contributions of each privacy unit to
    ``max_contributions`` (reference :108-150)."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        max_contributions = params.max_contributions
        col = backend.map_tuple(
            col, lambda pid, pk, v: (pid, (pk, v)),
            "Rekey to (privacy_id, (partition_key, value))")
        col = backend.sample_fixed_per_key(col, max_contributions,
                                           "Sample per privacy_id")
        report_generator.add_stage(
            f"User contribution bounding: randomly selected not more than "
            f"{max_contributions} contributions")
        col = collect_values_per_partition_key_per_privacy_id(col, backend)

        def unnest(pid_and_partition_values):
            pid, partition_values = pid_and_partition_values
            for pk, values in partition_values:
                yield (pid, pk), values

        col = backend.flat_map(col, unnest, "Unnest")
        return backend.map_values(
            col, aggregate_fn,
            "Apply aggregate_fn after per-privacy-id bounding")


class SamplingCrossPartitionContributionBounder(ContributionBounder):
    """L0-only bounding (reference :153-194): samples partitions per pid;
    assumes ``aggregate_fn`` bounds per-partition contributions (used with
    per-partition-sum clipping)."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        col = backend.map_tuple(
            col, lambda pid, pk, v: (pid, (pk, v)),
            "Rekey to (privacy_id, (partition_key, value))")
        col = backend.group_by_key(col, "Group by privacy_id")
        col = collect_values_per_partition_key_per_privacy_id(col, backend)
        sample = sampling_utils.choose_from_list_without_replacement
        sample_size = params.max_partitions_contributed
        col = backend.map_values(col, lambda a: sample(a, sample_size),
                                 "Sample partitions per privacy_id")

        def unnest(pid_and_partition_values):
            pid, partition_values = pid_and_partition_values
            for pk, values in partition_values:
                yield (pid, pk), values

        col = backend.flat_map(col, unnest, "Unnest per privacy_id")
        return backend.map_values(
            col, aggregate_fn,
            "Apply aggregate_fn after cross-partition bounding")


def collect_values_per_partition_key_per_privacy_id(col, backend):
    """(pid, Iterable[(pk, value)]) -> (pid, [(pk, [values])])
    (reference :197-224)."""

    def collect_fn(pk_values: Iterable):
        d = collections.defaultdict(list)
        for pk, value in pk_values:
            d[pk].append(value)
        return list(d.items())

    return backend.map_values(
        col, collect_fn, "Collect values per privacy_id and partition_key")
