"""Shape-bucketed request fusion: one warm program serves a whole
batch of tenant requests.

A resident service at heavy traffic dies by a thousand dispatches:
PR 11's serve path runs every request through its own compiled
program, so the warm path is bounded by per-request dispatch and
device occupancy, not by arithmetic. The utility-analysis sweep
already proves the cure on this codebase (``analysis/jax_sweep.py``
vectorizes hundreds of parameter configurations through one fused
kernel by adding a configuration axis); this module applies the same
trick to *real* DP requests:

* a **micro-batching layer between admission and the workers**: every
  admitted, fusable request lands in a shape bucket keyed by its
  tenant-independent params signature plus its pow2-padded
  ``(rows, partitions)`` shape; a bucket flushes as ONE batch when it
  reaches ``serve_fuse_batch`` requests or its bounded wait window
  (``serve_fuse_window_ms``) expires — latency is bounded, batching is
  opportunistic;
* **one compiled program per bucket**: the batch executor pads each
  member's encoded columns to the bucket edge (validity masks built
  alongside — :func:`pad_request_to_bucket` is the ONE blessed
  pad-mask constructor, enforced by the ``fusion-masking`` lint) and
  drives the whole batch through
  ``jax_engine.fused_aggregate_batch_kernel`` — a leading request axis
  vmapped over the solo kernel body. The second same-bucket batch
  captures zero new ``compile.program`` spans;
* **bit-identity per request** (PARITY row 35): per-request noise keys
  (counter RNG is keyed by content, so per-request streams stay
  pure), per-request row masks, and the padding-invariant row
  tie-breaks (``ops.counter_rng.row_bits``) make request b's slice of
  the batch bit-identical — released values AND kept sets — to the
  same request served solo;
* **bookkeeping exactly as today**: every request keeps its own
  two-phase budget reserve/commit, accountant audit record and books
  entry; the fusion layer only changes WHEN device work happens, never
  whose budget pays for it.

Bucket boundaries and the window are dp-safe ``plan/`` knobs
(``serve_fusion`` / ``serve_fuse_window_ms`` / ``serve_fuse_batch`` /
``serve_fuse_rows_floor``), dispatch composes with the
``kernel_backend`` knob, and live bucket occupancy is pushed into the
heartbeat's serve section so a stalled window self-diagnoses.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from pipelinedp_tpu import jax_engine as je
from pipelinedp_tpu.dp_engine import DataExtractors
from pipelinedp_tpu.obs import trace_context

#: Knob-seam defaults (registered in ``plan/knobs.py`` without module
#: seams — serve knobs resolve env > plan > default so that resolving
#: them never imports this package into batch mode). Values here are
#: the documented defaults the constructor falls back to.
DEFAULT_WINDOW_MS = 8
DEFAULT_MAX_BATCH = 8
DEFAULT_ROWS_FLOOR = 8192

#: Smallest legal row-bucket edge: the solo path never pads below 8192
#: rows (``jax_engine._pad_rows``), and a bucket edge below a member's
#: solo padding would change nothing for correctness (results are
#: padding-invariant) but would fragment the compile cache.
_ROWS_FLOOR_MIN = 8192

#: Seconds between queue-put retries / flush-loop beats while the
#: service drains (same beat as the serve workers).
_POLL_S = 0.02


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """One fused compile shape: the tenant-independent params
    signature (which fixes the FusedConfig, metrics, extractor shape
    and public-partition mode) plus the pow2-padded data shape. Every
    member of a bucket shares ONE compiled batched program per batch
    size."""
    signature: str
    rows: int        # pow2 row edge every member pads to
    partitions: int  # the solo path's _pad_pow2(P) — shared exactly
    fx_bits: int     # lane plan at the bucket's row edge
    # Vector compile shape, EXPLICIT: the params signature covers
    # these too, but the batched kernel's [B, rows, D] value plane and
    # its accumulator layout are incompatible across any difference
    # here — a D=64 and a D=256 request (or 'fx' vs 'f32' lanes) in
    # one bucket would be a shape error at best and silently mixed
    # noise calibration at worst. Keying them directly means no
    # signature-scheme change can ever re-merge them.
    vector_size: int = 0          # 0 = scalar request
    vector_norm_kind: str = ""    # "" = scalar request
    vector_accumulator: str = ""  # "" = scalar request

    @property
    def label(self) -> str:
        return f"{self.signature[:8]}@r{self.rows}p{self.partitions}"


def bucket_for(config, encoded, rows_floor: int) -> Optional[BucketKey]:
    """The shape half of a request's bucket key, or None when the
    request cannot fuse (empty vocabulary, streamed scale). The
    partition edge is EXACTLY the solo path's ``_pad_pow2(P)`` — the
    selection draw is shaped by it, so fused and solo must agree. The
    row edge is the solo path's own compile shape (``_pad_rows``: the
    next 8192-row tile multiple — the small pow2 edges 8192/16384/
    32768/... plus their tile multiples), floored at the pow2
    ``serve_fuse_rows_floor``: matching the solo shape keeps a fused
    member's row plane EXACTLY as large as its solo run (the CPU-proxy
    measurement shows the row plane dominates, so a 2x pow2 ceiling
    would hand back the whole fusion win as padded arithmetic), while
    the floor knob coarsens small-request buckets when the plan wants
    fewer compiled shapes. ANY edge choice >= the request's rows is
    bit-identical — released values are padding-invariant
    (``counter_rng.row_bits`` tie-breaks) — so the knob is dp-safe."""
    from pipelinedp_tpu import streaming

    P = len(encoded.pk_vocab)
    if P == 0:
        return None
    if streaming.should_stream(config, encoded.n_rows, None):
        return None
    rows = max(je._pad_rows(int(encoded.n_rows)),
               max(int(rows_floor), _ROWS_FLOOR_MIN))
    return BucketKey(
        signature="", rows=rows, partitions=je._pad_pow2(P),
        fx_bits=je.fused_fx_bits(config, rows),
        vector_size=int(config.vector_size or 0),
        vector_norm_kind=(config.vector_norm_kind.value
                          if config.vector_size and
                          config.vector_norm_kind else ""),
        vector_accumulator=(config.vector_accumulator
                            if config.vector_size else ""))


def pad_request_to_bucket(encoded, rows_pad: int, needs_values: bool
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray]:
    """THE pad-mask constructor (confined to this module by the
    ``fusion-masking`` lint): pad one request's encoded columns to the
    bucket's row edge and build the validity mask ALONGSIDE — the
    engine must never see padded rows without their mask, because only
    the mask keeps padding out of released values."""
    n = encoded.n_rows
    pid = np.zeros(rows_pad, np.int32)
    pid[:n] = encoded.pid
    pk = np.zeros(rows_pad, np.int32)
    pk[:n] = encoded.pk
    vals = np.asarray(encoded.values, dtype=np.float32)
    values = np.zeros((rows_pad,) + vals.shape[1:], np.float32)
    if needs_values:
        values[:n] = vals
    valid = np.arange(rows_pad) < n
    return pid, pk, values, valid


class FusedBatch:
    """One flushed bucket's worth of admitted requests, riding the
    service queue as a unit: a worker executes the whole batch through
    one program and finishes every member's pending individually."""

    __slots__ = ("key", "entries")

    def __init__(self, key: BucketKey, entries: List[Any]):
        self.key = key
        self.entries = entries


class _Bucket:
    __slots__ = ("key", "entries", "deadline")

    def __init__(self, key: BucketKey, deadline: float):
        self.key = key
        self.entries: List[Any] = []
        self.deadline = deadline


@dataclasses.dataclass
class _Admitted:
    """What ``offer`` learned about a fusable request, stashed on the
    pending so the executor never re-derives it."""
    signature: str
    config: Any
    encoded: Any
    bucket: BucketKey


class Fuser:
    """The micro-batching layer: ``offer()`` runs on the submitting
    caller's thread (the host-side encode is per-request work and
    parallelizes across callers), buckets live under one lock, and a
    single ``pdp-serve-fuse`` thread flushes expired windows. Batches
    enter the service's own bounded queue, so worker-pool sizing and
    graceful drain stay exactly the PR 11 story."""

    def __init__(self, service, clock, window_ms: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 rows_floor: Optional[int] = None):
        from pipelinedp_tpu import plan as plan_mod
        from pipelinedp_tpu.ingest.executor import _CaptureThread

        self._service = service
        self._clock = clock
        self.window_s = max(0.0, float(
            plan_mod.knob_value("serve_fuse_window_ms")
            if window_ms is None else window_ms) / 1000.0)
        self.max_batch = max(1, int(
            plan_mod.knob_value("serve_fuse_batch")
            if max_batch is None else max_batch))
        # Tile-rounded: a floor like 10000 would otherwise mint a row
        # shape no solo program ever compiles, fragmenting the compile
        # cache — the exact cost the floor exists to avoid.
        self.rows_floor = je._pad_rows(max(_ROWS_FLOOR_MIN, int(
            plan_mod.knob_value("serve_fuse_rows_floor")
            if rows_floor is None else rows_floor)))
        self._lock = threading.Lock()
        self._buckets: Dict[BucketKey, _Bucket] = {}
        self._queued = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = _CaptureThread(self._loop, "pdp-serve-fuse")
        self._thread.start()

    # --- admission side (caller thread) ---

    def offer(self, pending) -> bool:
        """Route one admitted pending into its shape bucket. Returns
        False when the request cannot fuse (non-fusable params, shapes
        that would stream, encode failure, fuser congestion or a
        closing service) — the caller then queues it solo, so fusion
        can only ever ADD a path, never lose a request."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.serve.service import params_signature

        request = pending.request
        try:
            if not je.params_are_fusable(request.params):
                return False
            config = je.FusedConfig.from_params(
                request.params, request.public_partitions is not None)
            extractors = (request.data_extractors
                          if request.data_extractors is not None
                          else DataExtractors())
            encoded = je.encode(
                request.dataset, extractors, config.vector_size,
                request.public_partitions,
                require_pid=not config.bounds_already_enforced)
            shape = bucket_for(config, encoded, self.rows_floor)
        except Exception:
            # A request the encode rejects fails identically on the
            # solo path, where the existing error-refusal story owns it.
            return False
        if shape is None:
            return False
        signature = params_signature(request)
        key = dataclasses.replace(shape, signature=signature)
        pending.fusion = _Admitted(signature=signature, config=config,
                                   encoded=encoded, bucket=key)
        ready: Optional[FusedBatch] = None
        with self._lock:
            if self._stop.is_set():
                return False
            if self._queued >= self._service.max_queue:
                # Bounded like the service queue: a congested fuser
                # sheds to the solo path instead of growing without
                # bound (which may then refuse queue_full — the same
                # backpressure story, one layer earlier).
                obs.inc("serve.fusion_shed")
                return False
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = _Bucket(key, self._clock.monotonic() +
                                 self.window_s)
                self._buckets[key] = bucket
            bucket.entries.append(pending)
            self._queued += 1
            if len(bucket.entries) >= self.max_batch:
                self._buckets.pop(key, None)
                self._queued -= len(bucket.entries)
                ready = FusedBatch(key, bucket.entries)
        # Past the locked insertion the pending is COMMITTED to the
        # fusion path (returning False now would double-route it), so
        # nothing below may take the offer down: a failure while
        # emitting a ready batch finishes its members as error
        # refusals (exactly once — finish() is checked), and a failure
        # before that leaves the pending safely in its bucket for the
        # window thread to flush.
        try:
            obs.inc("serve.fusion_offered")
            self._push_state()
            if ready is not None:
                self._emit(ready)
            else:
                self._wake.set()  # re-arm the flush loop's deadline
        except Exception as e:
            obs.event("serve.fusion_offer_error", error=repr(e))
            if ready is not None:
                for p in ready.entries:
                    if not p.done.is_set():
                        self._service._release_lease(p.lease)
                        p.finish("refusal", self._service._refuse(
                            p.lease.request_id, p.lease.tenant,
                            "error",
                            f"fusion emit failed: "
                            f"{type(e).__name__}: {e}"))
        return True

    # --- the window flush thread ---

    def _loop(self) -> None:
        # Beat at a quarter of the window (bounded [1ms, 20ms]) so a
        # deadline is overshot by at most ~window/4; offer() wakes the
        # loop early when a new bucket opens.
        beat = min(max(self.window_s / 4, 0.001), _POLL_S)
        while True:
            self._wake.wait(beat)
            self._wake.clear()
            if self._stop.is_set():
                return
            self._flush_due()

    def _flush_due(self, everything: bool = False) -> None:
        now = self._clock.monotonic()
        ready: List[FusedBatch] = []
        with self._lock:
            for key in list(self._buckets):
                bucket = self._buckets[key]
                if everything or now >= bucket.deadline:
                    self._buckets.pop(key, None)
                    self._queued -= len(bucket.entries)
                    ready.append(FusedBatch(key, bucket.entries))
        if ready:
            self._push_state()
        for batch in ready:
            self._emit(batch)

    def _emit(self, batch: FusedBatch) -> None:
        """Hand a flushed batch to the worker pool through the
        service's own bounded queue. During a close the workers drain
        the queue before exiting, so a put only fails once the pool is
        gone — those stragglers are refused exactly like the close()
        sweep refuses queued singles."""
        from pipelinedp_tpu import obs
        svc = self._service
        while True:
            try:
                svc._q.put(batch, timeout=_POLL_S)
                obs.inc("serve.fused_batches_queued")
                return
            except queue.Full:
                if svc._stop.is_set() and not svc._workers:
                    break
        for pending in batch.entries:
            svc._refuse_unworked(
                pending, "service closed before the fused batch "
                "reached a worker")

    # --- lifecycle / introspection ---

    def close(self) -> None:
        """Stop accepting offers, then flush every open window into
        the queue (the closing service still drains it) and join the
        flush thread. Stop-then-flush, in that order: an offer racing
        close either lands before the final flush (and is served) or
        sees the stop flag and falls back to the solo queue — no
        pending can strand in a bucket."""
        self._stop.set()
        self._wake.set()
        self._flush_due(everything=True)
        while self._thread.is_alive():
            self._thread.join(timeout=_POLL_S)
        from pipelinedp_tpu.obs import monitor as obs_monitor
        obs_monitor.update_fusion(None)

    def snapshot(self) -> Dict[str, Any]:
        """Live bucket occupancy for the heartbeat's serve section."""
        now = self._clock.monotonic()
        with self._lock:
            buckets = {
                b.key.label: {
                    "queued": len(b.entries),
                    "rows": b.key.rows,
                    "partitions": b.key.partitions,
                    "window_remaining_s": round(
                        max(0.0, b.deadline - now), 4),
                } for b in self._buckets.values()}
        return {"window_ms": round(self.window_s * 1000, 3),
                "max_batch": self.max_batch,
                "queued": sum(b["queued"] for b in buckets.values()),
                "buckets": buckets}

    def _push_state(self) -> None:
        from pipelinedp_tpu.obs import monitor as obs_monitor
        obs_monitor.update_fusion(self.snapshot())

    # --- the batch executor (worker thread) ---

    def execute(self, batch: FusedBatch) -> None:
        """Serve one flushed batch: per-request graph build + budget
        finalization under each warm entry's lock (exactly the solo
        admission-to-accountant sequence), then ONE batched program per
        stackable group, then each request's own release, commit, books
        and response. Every pending is finished exactly once on every
        path — the kill/failure semantics are the solo worker's."""
        from pipelinedp_tpu import obs

        ready = []
        for pending in batch.entries:
            # Explicit per-member context handoff: one fused batch
            # carries MANY requests' traces, so each member's phase-1
            # work is stamped under its own admission-time context.
            with trace_context.restore(pending.ctx):
                ctx = self._begin(pending)
            if ctx is not None:
                ready.append(ctx)
        if not ready:
            return
        groups: Dict[Tuple, List] = {}
        for ctx in ready:
            groups.setdefault(ctx.prep.stack_signature(),
                              []).append(ctx)
        if len(groups) > 1:
            obs.event("serve.fused_batch_split", bucket=batch.key.label,
                      groups=len(groups))
        for group in groups.values():
            self._run_group(batch.key, group)

    def _begin(self, pending):
        """Phase 1 for one request: the solo worker's front half —
        fault seam, warm entry, fresh accountant, graph build, budget
        finalization — stopping short of device dispatch. Returns an
        execution context, or None when the pending was already
        finished (injected kill, clean failure, or a visible fallback
        to solo execution)."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import audit as obs_audit
        from pipelinedp_tpu.obs import monitor as obs_monitor
        from pipelinedp_tpu.budget_accounting import NaiveBudgetAccountant
        from pipelinedp_tpu.resilience import faults

        svc = self._service
        request, lease = pending.request, pending.lease
        rid, tenant = lease.request_id, lease.tenant
        admitted: _Admitted = pending.fusion
        signature = admitted.signature
        obs_monitor.update_request(rid, phase="fused_batch",
                                   signature=signature,
                                   bucket=admitted.bucket.label)
        try:
            # The injected hard-kill seam, per request even mid-batch:
            # a FaultInjected models the process dying between the
            # durable reserve and any commit/release.
            faults.check_serve_request(pending.seq)
            entry, warm = svc._warm_entry(request, signature)
            obs.inc("serve.warm_hits" if warm else "serve.cold_builds")
            with entry.lock:
                try:
                    if hasattr(entry.backend, "rng_seed"):
                        entry.backend.rng_seed = request.rng_seed
                    accountant = NaiveBudgetAccountant(
                        total_epsilon=lease.epsilon,
                        total_delta=lease.delta)
                    accountant.bind_books(tenant, rid)
                    entry.engine.rebind_budget_accountant(accountant)
                    extractors = (request.data_extractors
                                  if request.data_extractors is not None
                                  else DataExtractors())
                    with obs_audit.books_context(tenant, rid):
                        with svc._tr.span("serve.request", cat="serve",
                                          tenant=tenant, warm=warm,
                                          fused=True) as sp:
                            result = entry.engine.aggregate(
                                request.dataset, request.params,
                                extractors,
                                public_partitions=(
                                    request.public_partitions))
                            accountant.compute_budgets()
                            prep = None
                            if isinstance(result, je.LazyFusedResult):
                                prep = result.prepare_fused(
                                    encoded=admitted.encoded)
                            if prep is None:
                                # Visible fallback: this request runs
                                # solo (its own program) but keeps the
                                # exact solo semantics — never silent.
                                # The offer-time encode rides along so
                                # the rows are never encoded twice.
                                obs.inc("serve.fusion_fallbacks")
                                obs.event("serve.fusion_fallback",
                                          request_id=rid, tenant=tenant,
                                          bucket=admitted.bucket.label)
                                if isinstance(result,
                                              je.LazyFusedResult):
                                    result._encoded_hint = (
                                        admitted.encoded)
                                results = list(result)
                except BaseException:
                    entry.engine.clear_budget_accountant()
                    raise
        except faults.FaultInjected as e:
            # Hard kill: the reserve stays spent (noise may have been
            # drawn); the warm slot is dropped; the submitter sees the
            # crash. Other batch members are untouched — each pending
            # resolves exactly once.
            svc._drop_entry(request, signature)
            obs.inc("serve.requests_killed")
            obs.event("serve.request_killed", request_id=rid,
                      tenant=tenant, error=repr(e))
            obs_monitor.unregister_request(rid)
            pending.finish("raise", e)
            return None
        except Exception as e:
            svc._drop_entry(request, signature)
            svc._release_lease(lease)
            obs_monitor.unregister_request(rid)
            pending.finish("refusal", svc._refuse(
                rid, tenant, "error", f"{type(e).__name__}: {e}"))
            return None
        if prep is None:
            svc._commit_and_respond(pending, accountant, results, warm,
                                    signature, sp.duration, fused=False)
            return None
        return _ExecCtx(pending=pending, entry=entry, warm=warm,
                        accountant=accountant, lazy=result, prep=prep,
                        build_s=sp.duration)

    def _run_group(self, key: BucketKey, group: List["_ExecCtx"]
                   ) -> None:
        """One stacked dispatch for a group of prepared requests (solo
        dispatch for a group of one — same bits, one less compile),
        then each member's release/commit/respond."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu import plan as plan_mod
        from pipelinedp_tpu.obs import audit as obs_audit
        from pipelinedp_tpu.resilience import faults

        svc = self._service
        config = group[0].prep.lazy._config
        try:
            if len(group) == 1:
                # A window that expired with one request gains nothing
                # from a B=1 batched program; the solo path is
                # bit-identical and already compiled. The offer-time
                # encode rides along as a hint so the fallback never
                # re-encodes the rows.
                ctx = group[0]
                ctx.lazy._encoded_hint = ctx.prep.encoded
                with trace_context.restore(ctx.pending.ctx):
                    with obs_audit.books_context(
                            ctx.pending.lease.tenant,
                            ctx.pending.lease.request_id):
                        results_by_ctx = {id(ctx): list(ctx.lazy)}
            else:
                # The planner resolution for this fused batch: one
                # resolve at the bucket shape (plan.applied events and
                # the walk's trace-time cap reads bucket here).
                plan_mod.resolve(
                    shape={"rows": int(key.rows),
                           "partitions": int(key.partitions),
                           "quantiles": len(config.percentiles or ())},
                    mesh=None)
                kernel_backend = str(
                    plan_mod.knob_value("kernel_backend"))
                if kernel_backend == "pallas" and config.percentiles:
                    # Same visible fallback the solo single-batch walk
                    # declares (no Pallas twin for the in-program walk).
                    obs.inc("kernel.fallbacks")
                    obs.event("kernel.fallback",
                              site="walk_subtree_counts",
                              reason="fused_batch_walk",
                              percentiles=len(config.percentiles))
                keep_h, raw_h, device_s = self._dispatch(
                    key, config, group, kernel_backend)
                results_by_ctx = {}
                for i, ctx in enumerate(group):
                    lease = ctx.pending.lease
                    with trace_context.restore(ctx.pending.ctx):
                        with obs_audit.books_context(lease.tenant,
                                                     lease.request_id):
                            out = ctx.lazy.finish_from_fused(
                                ctx.prep, keep_h[i],
                                {k: v[i] for k, v in raw_h.items()},
                                key.fx_bits)
                    ctx.lazy.timings["device_s"] = device_s / len(group)
                    results_by_ctx[id(ctx)] = out
                obs.inc("serve.fused_batches")
                obs.inc("serve.fused_requests", len(group))
                obs.event("serve.fused_batch", bucket=key.label,
                          size=len(group),
                          device_s=round(device_s, 6))
        except faults.FaultInjected as e:
            # A kill during the shared dispatch takes the whole batch
            # down the hard-kill path: every reserve stays spent, every
            # submitter sees the crash — once each.
            for ctx in group:
                svc._drop_entry(ctx.pending.request,
                                ctx.pending.fusion.signature)
                obs.inc("serve.requests_killed")
                self._unregister(ctx)
                ctx.pending.finish("raise", e)
            return
        except Exception as e:
            # Clean failure before any member's DP release existed:
            # refund every non-replayed reserve and refuse each request
            # — the solo clean-failure semantics, batch-wide.
            for ctx in group:
                svc._drop_entry(ctx.pending.request,
                                ctx.pending.fusion.signature)
                svc._release_lease(ctx.pending.lease)
                self._unregister(ctx)
                ctx.pending.finish("refusal", svc._refuse(
                    ctx.pending.lease.request_id,
                    ctx.pending.lease.tenant, "error",
                    f"{type(e).__name__}: {e}"))
            return
        for ctx in group:
            svc._commit_and_respond(
                ctx.pending, ctx.accountant, results_by_ctx[id(ctx)],
                ctx.warm, ctx.pending.fusion.signature,
                ctx.build_s + (ctx.lazy.timings or {}).get("device_s",
                                                           0.0),
                fused=len(group) > 1)

    def _dispatch(self, key: BucketKey, config, group,
                  kernel_backend: str):
        """Pad, stack, run the ONE batched program, fetch once."""
        svc = self._service
        padded = [pad_request_to_bucket(ctx.prep.encoded, key.rows,
                                        config.needs_values)
                  for ctx in group]
        # The batch span carries per-member child links: a comma-joined
        # list of the members' trace ids (scalar, so the activity ring
        # keeps it) — each member's own chain stays separable while the
        # shared dispatch names everyone it served.
        members = ",".join(
            (ctx.pending.ctx.trace_id
             if ctx.pending.ctx is not None else "-")
            for ctx in group)
        with svc._tr.span("serve.fused_dispatch", cat="serve",
                          bucket=key.label, size=len(group),
                          members=members) as sp:
            bpid = jnp.asarray(np.stack([p[0] for p in padded]))
            bpk = jnp.asarray(np.stack([p[1] for p in padded]))
            bvalues = jnp.asarray(np.stack([p[2] for p in padded]))
            bvalid = jnp.asarray(np.stack([p[3] for p in padded]))
            bscales = jnp.asarray(
                np.stack([ctx.prep.scales for ctx in group]))
            btables = jnp.asarray(
                np.stack([ctx.prep.keep_table for ctx in group]))
            bthr = jnp.asarray([ctx.prep.thr for ctx in group],
                               jnp.float32)
            bss = jnp.asarray([ctx.prep.s_scale for ctx in group],
                              jnp.float32)
            bmc = jnp.asarray([ctx.prep.min_count for ctx in group],
                              jnp.float32)
            brpu = jnp.asarray([ctx.prep.rows_per_uid for ctx in group],
                               jnp.float32)
            bkeys = jnp.stack([ctx.prep.key for ctx in group])
            keep, raw = je.fused_aggregate_batch_kernel(
                config, key.partitions, bpid, bpk, bvalues, bvalid,
                bscales, btables, bthr, bss, bmc, brpu, bkeys,
                fx_bits=key.fx_bits, kernel_backend=kernel_backend)
            keep_h = np.asarray(keep)
            raw_h = {k: np.asarray(v) for k, v in raw.items()}
        return keep_h, raw_h, sp.duration

    @staticmethod
    def _unregister(ctx) -> None:
        from pipelinedp_tpu.obs import monitor as obs_monitor
        obs_monitor.unregister_request(ctx.pending.lease.request_id)


@dataclasses.dataclass
class _ExecCtx:
    """One batch member past phase 1: everything phase 2 needs."""
    pending: Any
    entry: Any
    warm: bool
    accountant: Any
    lazy: Any
    prep: Any
    build_s: float
