"""pipelinedp_tpu.serve — the resident multi-tenant aggregation service.

A thin package over the existing engine: durable per-tenant budget
ledgers (``budget_ledger``), admission control + bounded queue + warm
program reuse (``service``). In-process API first::

    from pipelinedp_tpu import serve

    svc = serve.Service("/var/pdp", tenants={"acme": (4.0, 1e-6)})
    out = svc.submit(serve.ServeRequest(
        tenant="acme", params=params, dataset=ds,
        epsilon=0.5, delta=1e-8))
    if out.ok:
        dict(out.results)
    else:
        out.reason, out.detail   # "overdraw" / "queue_full" / ...

Batch mode never imports this package (enforced by ``make noserve``);
the serve path runs the batch engine's own code, so serve-on/off is
DP-bit-identical (PARITY row 34).
"""

from pipelinedp_tpu.serve.budget_ledger import (BudgetLease, LedgerError,
                                                Overdraw,
                                                TenantBudgetLedger,
                                                TenantMismatch,
                                                UnknownTenant,
                                                tenant_slug)
from pipelinedp_tpu.serve.service import (REFUSAL_REASONS, Refusal,
                                          Service, ServeRequest,
                                          ServeResponse,
                                          params_signature)

__all__ = [
    "BudgetLease", "LedgerError", "Overdraw", "TenantBudgetLedger",
    "TenantMismatch", "UnknownTenant", "tenant_slug",
    "REFUSAL_REASONS", "Refusal", "Service", "ServeRequest",
    "ServeResponse", "params_signature",
]
