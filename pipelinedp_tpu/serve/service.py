"""Resident multi-tenant DP aggregation service.

``Service`` turns the one-process-one-job library into a system: it
stays resident, accepts a stream of aggregation requests for many
tenants, and routes them through long-lived warm state —

* **admission control** on the caller's thread, BEFORE any compute:
  malformed requests, per-tenant in-flight caps, queue-full
  backpressure and budget overdraws all come back as structured
  :class:`Refusal` values (never exceptions), and the budget debit is
  durably reserved in the tenant's ledger before the request is even
  queued;
* a **bounded queue** drained by a small pool of ingest-discipline
  worker threads (``pdp-serve-*`` ``_CaptureThread``\\ s, poll-with-
  timeout waits, graceful drain on ``close()`` — the zero-orphan
  lifecycle the streaming executor established);
* a **warm registry** of resident ``DPEngine`` + backend instances
  keyed by (tenant, params-signature): a repeat request rebinds a
  fresh per-request accountant into the resident engine
  (``DPEngine.rebind_budget_accountant``) and hits the process's warm
  jitted programs — no recompile, no re-probe — while every request
  still gets its own two-phase accountant, audit record and books
  entry.

The transport is deliberately in-process (``submit(request)`` →
response/refusal): the service is a thin package over the existing
engine, batch mode is untouched, and serve-on/off is DP-bit-identical
(PARITY row 34) because the serve path runs exactly the batch path's
code with exactly the batch path's inputs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import queue
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from pipelinedp_tpu.aggregate_params import AggregateParams, Metrics
from pipelinedp_tpu.budget_accounting import (Budget,
                                              NaiveBudgetAccountant)
from pipelinedp_tpu.dp_engine import DataExtractors, DPEngine
from pipelinedp_tpu.obs import trace_context
from pipelinedp_tpu.serve.budget_ledger import (BudgetLease,
                                                DuplicateRequest,
                                                LedgerError,
                                                Overdraw,
                                                TenantBudgetLedger,
                                                UnknownTenant,
                                                tenant_slug)

#: Admission-control env knobs (constructor args win; see the README
#: knob table). Queue depth bounds memory under backpressure; the
#: per-tenant in-flight cap keeps one tenant from monopolizing the
#: worker pool; the rows/rate quotas refuse oversized or too-frequent
#: requests BEFORE any budget reserve or compute (refusal kind
#: ``quota`` — ROADMAP serve item (b)).
QUEUE_ENV = "PIPELINEDP_TPU_SERVE_QUEUE"
INFLIGHT_ENV = "PIPELINEDP_TPU_SERVE_INFLIGHT"
WORKERS_ENV = "PIPELINEDP_TPU_SERVE_WORKERS"
ROWS_ENV = "PIPELINEDP_TPU_SERVE_ROWS"
RATE_ENV = "PIPELINEDP_TPU_SERVE_REQS_PER_S"

DEFAULT_QUEUE_DEPTH = 16
DEFAULT_INFLIGHT_PER_TENANT = 4
DEFAULT_WORKERS = 2
#: 0 = unlimited (the default: quotas are opt-in caps).
DEFAULT_MAX_ROWS = 0
DEFAULT_REQS_PER_S = 0
#: Seconds of admission history the per-tenant rate quota windows over.
_RATE_WINDOW_S = 1.0

#: Seconds between cancel polls while a worker blocks on the queue
#: (same beat as the ingest executor).
_POLL_S = 0.02


@dataclasses.dataclass
class ServeRequest:
    """One aggregation request against a tenant's budget.

    ``epsilon``/``delta`` are the request's DEMAND on the tenant's
    durable ledger — they become the per-request accountant's totals,
    so the ledger's debit and the accountant's distribution agree
    exactly. ``rng_seed`` fixes the noise stream (tests, replayable
    pipelines); None draws fresh noise per request.

    ``kind="tune"`` asks the utility-analysis megasweep which (bounds,
    budget split, selection strategy) would minimize expected error at
    the given (epsilon, delta) — BEFORE spending them. A tune request
    is admitted, quota'd, books-stamped and refused exactly like an
    aggregate, but debits ZERO (ε, δ) from the tenant's ledger:
    utility analysis releases error ESTIMATES of hypothetical
    mechanisms, never private data (the reference's analysis engine
    makes the same argument). ``tune_parameters`` optionally carries a
    ``parameter_tuning.ParametersToTune``; None tunes the bounds the
    single analyzed metric supports."""
    tenant: str
    params: AggregateParams
    dataset: Any
    epsilon: float
    delta: float = 0.0
    data_extractors: Optional[DataExtractors] = None
    public_partitions: Any = None
    rng_seed: Optional[int] = None
    request_id: Optional[str] = None
    kind: str = "aggregate"
    tune_parameters: Any = None


@dataclasses.dataclass
class ServeResponse:
    """A served request: the released metrics plus the books."""
    request_id: str
    tenant: str
    results: List[Tuple[Any, Any]]
    remaining: Budget
    warm: bool
    signature: str
    wall_s: float
    audit: Dict[str, Any]
    #: The request's causal trace id (obs.trace_context) — the handle
    #: for ``/trace/<id>`` and ``store --summarize --trace-id``.
    trace_id: Optional[str] = None

    @property
    def ok(self) -> bool:
        return True


#: The closed set of refusal reasons — admission control speaks a
#: vocabulary, not free text (``detail`` carries the prose).
REFUSAL_REASONS = ("overdraw", "malformed", "duplicate", "quota",
                   "queue_full", "tenant_busy", "shutdown", "degraded",
                   "error")


@dataclasses.dataclass
class Refusal:
    """A refused request: structured, never an exception. ``reason``
    is one of :data:`REFUSAL_REASONS`; ``remaining`` is attached where
    it informs the caller (overdraw)."""
    request_id: str
    tenant: str
    reason: str
    detail: str
    remaining: Optional[Budget] = None

    @property
    def ok(self) -> bool:
        return False


def params_signature(request: ServeRequest) -> str:
    """The warm-registry key half that names WHAT program a request
    needs: the full aggregation params, the public-partition mode and
    the extractor shape. Deliberately NOT the rng seed — the seed is
    per-request noise state, set on the resident backend under the
    entry lock, so requests that differ only in their noise stream
    still share one warm engine. Two requests with equal signatures
    (and tenant) may share a resident engine; the jitted program cache
    underneath additionally keys on array shapes, so a signature hit
    with new shapes simply compiles one more specialization."""
    ext = request.data_extractors
    basis = "|".join((
        repr(request.params),
        repr(sorted(map(repr, request.public_partitions))
             if request.public_partitions is not None else None),
        repr((ext is not None and ext.privacy_id_extractor is not None,
              ext is not None and ext.partition_extractor is not None,
              ext is not None and ext.value_extractor is not None)),
        # The request kind: a tune and an aggregate at the same params
        # run DIFFERENT programs (the megasweep vs the engine), so
        # they must never share a warm slot.
        request.kind,
    ))
    return hashlib.sha256(basis.encode("utf-8")).hexdigest()[:16]


class _WarmEntry:
    """One resident (tenant, signature) slot: engine + backend + a
    lock serializing same-key requests (an engine holds per-request
    accountant state while it runs)."""

    def __init__(self, engine: DPEngine, backend: Any):
        self.engine = engine
        self.backend = backend
        self.lock = threading.Lock()
        self.hits = 0


class _Pending:
    """A submitted request waiting for its worker: the caller blocks
    on ``done``; ``outcome`` is ("response", r) / ("refusal", r) /
    ("raise", exc) — the last one models a request the injected kill
    took down, re-raised on the submitting thread."""

    def __init__(self, request: ServeRequest, lease: BudgetLease,
                 seq: int):
        self.request = request
        self.lease = lease
        self.seq = seq
        #: The submitting caller's trace context, captured HERE because
        #: contextvars do not flow into threads: the worker / fuser /
        #: release tail each re-bind it explicitly
        #: (``trace_context.restore``), which is what keeps one
        #: request's spans a single causal chain across the handoffs.
        self.ctx = trace_context.current()
        self.done = threading.Event()
        self.outcome: Optional[Tuple[str, Any]] = None
        #: Set by the fusion layer at offer time (serve/fusion.py):
        #: the request's signature, encoded columns and shape bucket,
        #: so the batch executor never re-derives them.
        self.fusion: Optional[Any] = None
        #: Set by the worker that picks this request up: frees the
        #: in-flight slot and live id. Run by ``finish`` BEFORE the
        #: submitter is unblocked — a caller whose submit() returned
        #: must be able to resubmit the id (or fill the slot)
        #: immediately, not race the worker's cleanup.
        self.teardown: Optional[Any] = None

    def finish(self, kind: str, value: Any) -> None:
        teardown, self.teardown = self.teardown, None
        if teardown is not None:
            teardown()
        self.outcome = (kind, value)
        self.done.set()


class Service:
    """The resident service. Construct once, ``register_tenant`` (or
    pass ``tenants=``), then ``submit`` from any thread; ``close()``
    (or the context manager) drains the queue and joins every worker.

    Directory layout under ``ledger_dir``::

        budgets/budget-<tenant-slug>.json   durable budget ledgers
        books/<tenant-slug>/run_ledger.jsonl   per-tenant request books
    """

    def __init__(self, ledger_dir: str,
                 tenants: Optional[Dict[str, Tuple[float, float]]] = None,
                 *,
                 max_queue: Optional[int] = None,
                 max_inflight_per_tenant: Optional[int] = None,
                 workers: Optional[int] = None,
                 max_rows_per_request: Optional[int] = None,
                 max_reqs_per_s: Optional[int] = None,
                 fusion: Optional[bool] = None,
                 fuse_window_ms: Optional[int] = None,
                 fuse_max_batch: Optional[int] = None,
                 fuse_rows_floor: Optional[int] = None,
                 backend_factory=None,
                 clock=None):
        from pipelinedp_tpu import obs
        self.ledger_dir = str(ledger_dir)
        self.budgets = TenantBudgetLedger(
            os.path.join(self.ledger_dir, "budgets"))
        self.max_queue = int(
            os.environ.get(QUEUE_ENV, DEFAULT_QUEUE_DEPTH)
            if max_queue is None else max_queue)
        self.max_inflight_per_tenant = int(
            os.environ.get(INFLIGHT_ENV, DEFAULT_INFLIGHT_PER_TENANT)
            if max_inflight_per_tenant is None
            else max_inflight_per_tenant)
        n_workers = int(os.environ.get(WORKERS_ENV, DEFAULT_WORKERS)
                        if workers is None else workers)
        # Service-wide quota defaults (0 = unlimited); register_tenant
        # may tighten them per tenant.
        self.max_rows_per_request = int(
            os.environ.get(ROWS_ENV, DEFAULT_MAX_ROWS)
            if max_rows_per_request is None else max_rows_per_request)
        self.max_reqs_per_s = int(
            os.environ.get(RATE_ENV, DEFAULT_REQS_PER_S)
            if max_reqs_per_s is None else max_reqs_per_s)
        self._quotas: Dict[str, Dict[str, int]] = {}
        self._admit_times: Dict[str, Any] = {}
        self._backend_factory = backend_factory or self._default_backend
        if clock is None:
            from pipelinedp_tpu.resilience.clock import SystemClock
            clock = SystemClock()
        self._clock = clock
        #: Service birth on the injectable clock — the denominator of
        #: the per-tenant budget burn-rate gauges.
        self._t0 = self._clock.monotonic()
        self._tr = obs.run_tracer(clock=clock)
        self._q: queue.Queue = queue.Queue(maxsize=self.max_queue)
        self._admit = threading.Lock()
        self._inflight: Dict[str, int] = {}
        #: (tenant, request id) pairs currently live in THIS process
        #: (admitted, not yet finished), guarded by ``_admit``. A
        #: duplicate id is refused while its original is in flight —
        #: the ledger's reserved-dedup lease is for restart replay
        #: only, and without this guard a client retry racing its own
        #: original would release two noisy views on one charge. Keyed
        #: per tenant, like the ledger's debits: tenants never collide
        #: on each other's ids.
        self._live: set = set()
        self._registry: Dict[Tuple[str, str], _WarmEntry] = {}
        self._registry_lock = threading.Lock()
        self._books_lock = threading.Lock()
        self._books_stores: Dict[str, Any] = {}
        self._env: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._closed = threading.Event()
        self._stop = threading.Event()
        from pipelinedp_tpu.ingest.executor import _CaptureThread
        self._workers = [
            _CaptureThread(self._worker_loop, f"pdp-serve-{i}")
            for i in range(max(1, n_workers))]
        for t in self._workers:
            t.start()
        # Shape-bucketed request fusion (serve/fusion.py): the dp-safe
        # ``serve_fusion`` knob arms it (constructor arg wins); off by
        # default, and on/off is DP-bit-identical per request (PARITY
        # row 35) — the knob is purely a throughput/latency trade.
        if fusion is None:
            from pipelinedp_tpu import plan as plan_mod
            fusion = bool(plan_mod.knob_value("serve_fusion"))
        self._fuser = None
        if fusion:
            from pipelinedp_tpu.serve import fusion as fusion_mod
            self._fuser = fusion_mod.Fuser(
                self, clock=self._clock, window_ms=fuse_window_ms,
                max_batch=fuse_max_batch, rows_floor=fuse_rows_floor)
        # Degraded mode: a process whose runtime is wedged (the health
        # probe degraded it to CPU, a mesh lost its last participant)
        # refuses EVERY submit with a structured "degraded" refusal
        # BEFORE any budget reserve — never a silent wrong-shape run,
        # never a spent charge for work that can't be trusted. Armed
        # here from resilience.health.DEGRADED_ENV, or at runtime via
        # set_degraded()/clear_degraded().
        self._degraded: Optional[str] = None
        from pipelinedp_tpu.resilience.health import DEGRADED_ENV
        if os.environ.get(DEGRADED_ENV):
            self.set_degraded(
                f"{DEGRADED_ENV} is set: the runtime came up degraded "
                "(health probe fell back); refusing before reserve")
        for tenant, (eps, delta) in (tenants or {}).items():
            self.register_tenant(tenant, eps, delta)
        # The read-only introspection endpoint (obs/http.py): off
        # unless PIPELINEDP_TPU_METRICS_PORT is set; a bind failure is
        # an event, never a startup failure. Bound into THIS lifecycle:
        # close() stops it, so the service leaves zero orphan threads.
        from pipelinedp_tpu.obs import http as obs_http
        self._http = obs_http.maybe_start()
        self._push_tenant_state()
        self._push_occupancy()
        obs.event("serve.started", workers=len(self._workers),
                  max_queue=self.max_queue,
                  max_inflight_per_tenant=self.max_inflight_per_tenant,
                  fusion=bool(self._fuser is not None),
                  metrics_port=(self._http.port
                                if self._http is not None else None),
                  ledger_dir=self.ledger_dir)

    # --- lifecycle ---

    @staticmethod
    def _default_backend(request: ServeRequest):
        from pipelinedp_tpu.backends import JaxBackend
        return JaxBackend(rng_seed=request.rng_seed)

    def register_tenant(self, tenant: str, total_epsilon: float,
                        total_delta: float,
                        max_rows_per_request: Optional[int] = None,
                        max_reqs_per_s: Optional[int] = None) -> Budget:
        """Open (or re-open) a tenant's durable budget ledger; returns
        the remaining budget — which a restart replays from disk.
        ``max_rows_per_request`` / ``max_reqs_per_s`` tighten the
        service-wide quotas for THIS tenant (0 = unlimited; None keeps
        the service default): oversized or too-frequent requests are
        refused as ``quota`` before any budget reserve or compute."""
        quotas = {}
        if max_rows_per_request is not None:
            quotas["rows"] = int(max_rows_per_request)
        if max_reqs_per_s is not None:
            quotas["reqs_per_s"] = int(max_reqs_per_s)
        if quotas:
            self._quotas[tenant] = quotas
        remaining = self.budgets.open_tenant(tenant, total_epsilon,
                                             total_delta)
        self._push_tenant_state()
        return remaining

    def _tenant_quota(self, tenant: str, kind: str, default: int) -> int:
        return int(self._quotas.get(tenant, {}).get(kind, default))

    # --- degraded mode ---

    def set_degraded(self, detail: str) -> None:
        """Flip the service into degraded mode: every subsequent
        ``submit`` is refused with reason ``"degraded"`` before any
        budget reserve. The state is pushed into the heartbeat's
        ``serve.health`` section so an operator sees WHY traffic is
        bouncing, not just that it is."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        self._degraded = str(detail)
        obs.inc("serve.degraded_entered")
        obs.event("serve.degraded", detail=self._degraded)
        obs_monitor.update_serve_health(
            {"state": "degraded", "detail": self._degraded})

    def clear_degraded(self) -> None:
        """Leave degraded mode; submissions are admitted again."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        if self._degraded is None:
            return
        self._degraded = None
        obs.event("serve.degraded_cleared")
        obs_monitor.update_serve_health({"state": "ok"})

    def close(self) -> None:
        """Graceful drain: refuse new submissions, serve everything
        already queued, then stop and join every worker (zero orphan
        ``pdp-serve-*`` threads — the executor discipline). Taking the
        admission lock to flip ``_closed`` closes the race with an
        in-flight ``submit()``: an admitter that already passed the
        closed check finishes its enqueue before we proceed, and the
        post-join sweep below refunds + refuses anything the departed
        workers left behind — no submitter ever blocks forever."""
        from pipelinedp_tpu import obs
        with self._admit:
            self._closed.set()
        # Flush every open fusion window BEFORE stopping the workers:
        # the flushed batches enter the queue and drain normally, so a
        # graceful close serves everything already admitted.
        if self._fuser is not None:
            self._fuser.close()
        self._stop.set()
        for t in self._workers:
            while t.is_alive():
                t.join(timeout=_POLL_S)
        self._workers = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            pendings = (item.entries if hasattr(item, "entries")
                        else [item])
            for pending in pendings:
                self._refuse_unworked(
                    pending, "service closed before a worker picked "
                    "this request up")
        if self._http is not None:
            self._http.stop()
            self._http = None
        obs.event("serve.closed")

    def _refuse_unworked(self, pending: "_Pending",
                         detail: str) -> None:
        """Refuse a pending no worker will ever serve (the close()
        sweep, a fused batch stranded by a closing queue): refund the
        reserve unless replayed, free the live id, finish the
        submitter exactly once."""
        from pipelinedp_tpu.obs import monitor as obs_monitor
        tenant, rid = pending.lease.tenant, pending.lease.request_id
        self._release_lease(pending.lease)
        with self._admit:
            self._live.discard((tenant, rid))
        obs_monitor.unregister_request(rid)
        pending.finish("refusal", self._refuse(
            rid, tenant, "shutdown",
            detail + "; " + ("the replayed reserve stays spent (the "
                             "pre-restart attempt may have drawn noise)"
                             if pending.lease.replayed else
                             "the reserve was refunded")))

    def __enter__(self) -> "Service":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # --- admission control (caller thread; never any compute) ---

    def _validate(self, request: ServeRequest) -> Optional[str]:
        # submit() has already refused a non-ServeRequest before any
        # attribute of it was touched.
        if not request.tenant or not isinstance(request.tenant, str):
            return "tenant must be a non-empty string"
        if not isinstance(request.params, AggregateParams):
            return ("params must be an AggregateParams, got "
                    f"{type(request.params).__name__}")
        try:
            if request.dataset is None or len(request.dataset) == 0:
                return "dataset must be non-empty"
        except TypeError:
            return "dataset must be sized (rows or ArrayDataset)"
        if not (isinstance(request.epsilon, (int, float))
                and request.epsilon > 0):
            return f"epsilon must be positive, got {request.epsilon!r}"
        if not (isinstance(request.delta, (int, float))
                and request.delta >= 0):
            return f"delta must be >= 0, got {request.delta!r}"
        if request.kind not in ("aggregate", "tune"):
            return ("kind must be 'aggregate' or 'tune', got "
                    f"{request.kind!r}")
        if request.kind == "tune":
            metrics_list = list(request.params.metrics or [])
            if len(metrics_list) != 1:
                return ("tune requests analyze exactly one metric, got "
                        f"{[str(m) for m in metrics_list]!r}")
        return None

    def submit(self, request: ServeRequest):
        """Admit, queue and serve one request; blocks until its
        response (or refusal) is ready. Thread-safe — concurrent
        callers model concurrent tenants. The call sequence is the
        contract: a request REFUSED here has spent nothing and run
        nothing (the overdraw check happens before any compute), and
        a request admitted here has its (eps, delta) durably reserved
        before the queue ever sees it. A request id whose original is
        still in flight is refused as 'duplicate' — admitting the
        retry would let one durable debit release two noisy views."""
        if not isinstance(request, ServeRequest):
            # Refuse before touching any attribute — a non-ServeRequest
            # has no request_id/tenant to read.
            return self._refuse(
                f"req-{uuid.uuid4().hex[:12]}", "<unknown>", "malformed",
                f"expected ServeRequest, got {type(request).__name__}")
        # Normalized to str up front: the ledger stores str(request_id)
        # in its leases, and _live teardown keys must match admission's.
        # Only None/"" mean "absent" — a falsy id like 0 is a real id,
        # and generating a fresh one for it would void exactly-once.
        if request.request_id is None or request.request_id == "":
            rid = f"req-{uuid.uuid4().hex[:12]}"
        else:
            rid = str(request.request_id)
        # One trace context per request, bound on the CALLER's thread
        # for the whole admission path: every span/event under it is
        # stamped (trace_id, tenant, request_id), and _Pending captures
        # it for the explicit handoffs to the fuser/worker threads.
        # Telemetry-only — binding never touches DP arithmetic (PARITY
        # row 42).
        with trace_context.bind(tenant=request.tenant, request_id=rid):
            return self._submit_bound(request, rid)

    def _submit_bound(self, request: ServeRequest, rid: str):
        """The body of ``submit`` under the request's bound trace
        context (same contract, same return values)."""
        tenant = request.tenant
        if self._closed.is_set():
            return self._refuse(rid, tenant, "shutdown",
                                "service is draining; submit refused")
        degraded = self._degraded
        if degraded is not None:
            # Refused BEFORE any budget reserve: a degraded process
            # must not spend a tenant's charge on untrustworthy work.
            return self._refuse(rid, tenant, "degraded", degraded)
        detail = self._validate(request)
        if detail is not None:
            return self._refuse(rid, tenant, "malformed", detail)
        if not self.budgets.has_tenant(tenant):
            # Before the tentative admission: a resident process must
            # not grow per-tenant state (in-flight slots, ledger
            # locks) for arbitrary unknown tenant names.
            return self._refuse(
                rid, tenant, "malformed",
                f"tenant '{tenant}' has no ledger under "
                f"{self.budgets.directory}; register_tenant first")
        # Row quota: stateless, so it refuses before any shared state
        # is touched — an oversized request never costs a slot, a
        # reserve, or any compute.
        rows_cap = self._tenant_quota(tenant, "rows",
                                      self.max_rows_per_request)
        if rows_cap > 0:
            try:
                n_rows = len(request.dataset)
            except TypeError:  # _validate vouched it is sized
                n_rows = 0
            if n_rows > rows_cap:
                return self._refuse(
                    rid, tenant, "quota",
                    f"request carries {n_rows} rows, over tenant "
                    f"'{tenant}'s per-request row quota of {rows_cap}")
        full_detail = (f"request queue is full ({self.max_queue} "
                       "deep); back off and resubmit")
        verdict: Optional[Tuple[str, str]] = None
        with self._admit:
            if self._closed.is_set():
                verdict = ("shutdown",
                           "service is draining; submit refused")
            elif (tenant, rid) in self._live:
                verdict = (
                    "duplicate",
                    f"request id '{rid}' is already in flight; one "
                    "charge can never release two noisy views — wait "
                    "for the original to finish or use a fresh id")
            else:
                rate_cap = self._tenant_quota(tenant, "reqs_per_s",
                                              self.max_reqs_per_s)
                rate_verdict = (self._check_rate(tenant, rate_cap)
                                if rate_cap > 0 else None)
                inflight = self._inflight.get(tenant, 0)
                if rate_verdict is not None:
                    verdict = rate_verdict
                elif inflight >= self.max_inflight_per_tenant:
                    verdict = (
                        "tenant_busy",
                        f"tenant '{tenant}' already has {inflight} "
                        f"request(s) in flight (cap "
                        f"{self.max_inflight_per_tenant})")
                elif self._q.full():
                    verdict = ("queue_full", full_detail)
                else:
                    # Tentative admission: hold the in-flight slot and
                    # the live id while the durable (fsync'd) reserve
                    # runs OUTSIDE the global lock — one tenant's disk
                    # sync must not serialize every other tenant's
                    # admission.
                    self._inflight[tenant] = inflight + 1
                    self._live.add((tenant, rid))
                    if rate_cap > 0:
                        self._admit_times.setdefault(
                            tenant, []).append(self._clock.monotonic())
        if verdict is not None:
            return self._refuse(rid, tenant, *verdict)
        if request.kind == "tune":
            # Utility analysis releases no private data — the request's
            # (epsilon, delta) are the HYPOTHETICAL budget the error
            # model simulates, not a demand on the ledger. A synthetic
            # zero-amount lease (state="tune", never written to disk)
            # rides the same pending plumbing; _release_lease no-ops on
            # it and the worker routes it through _execute_tune /
            # _respond_tune, leaving the durable ledger untouched.
            lease = BudgetLease(tenant=tenant, request_id=rid,
                                epsilon=0.0, delta=0.0, state="tune")
            return self._enqueue_admitted(request, lease, rid, tenant)
        try:
            lease = self.budgets.reserve(tenant, rid, request.epsilon,
                                         request.delta)
        except Overdraw as e:
            self._rollback_admission(tenant, rid)
            return self._refuse(
                rid, tenant, "overdraw",
                f"insufficient budget: requested {e.requested}, "
                f"remaining {e.remaining}, shortfall "
                f"{e.shortfall}", remaining=e.remaining)
        except DuplicateRequest as e:
            self._rollback_admission(tenant, rid)
            return self._refuse(rid, tenant, "duplicate", str(e))
        except UnknownTenant as e:
            self._rollback_admission(tenant, rid)
            return self._refuse(rid, tenant, "malformed", str(e))
        except LedgerError as e:
            # e.g. a restart replay whose (eps, delta) do not match
            # the reserved debit's amounts.
            self._rollback_admission(tenant, rid)
            return self._refuse(rid, tenant, "malformed", str(e))
        except BaseException:
            self._rollback_admission(tenant, rid)
            raise
        return self._enqueue_admitted(request, lease, rid, tenant)

    def _enqueue_admitted(self, request: ServeRequest,
                          lease: BudgetLease, rid: str, tenant: str):
        """The post-reserve half of ``submit``: register with the
        monitor, route through fusion (aggregate kind only) or the solo
        queue, block for the outcome. Shared by aggregates (durable
        lease) and tunes (synthetic zero-debit lease)."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        full_detail = (f"request queue is full ({self.max_queue} "
                       "deep); back off and resubmit")
        verdict: Optional[Tuple[str, str]] = None
        # The admission span is the request's causal ROOT: _Pending is
        # constructed inside it, so the captured context carries this
        # span as parent — the worker/fuser/commit spans nest beneath
        # it and the Chrome-trace flow arc starts on this thread.
        with self._tr.span("serve.admit", cat="serve", tenant=tenant,
                           kind=request.kind):
            # Register BEFORE the enqueue: the worker's
            # update/unregister must always follow the registration, or
            # a fast completion would leave a phantom live request in
            # every later heartbeat.
            obs_monitor.register_request(rid, tenant=tenant,
                                         phase="queued",
                                         kind=request.kind)
            routed = False
            with self._admit:
                if self._closed.is_set():  # raced close()
                    verdict = ("shutdown",
                               "service is draining; submit refused")
                else:
                    pending = _Pending(request, lease, self._seq)
                    self._seq += 1
            if (verdict is None and self._fuser is not None
                    and request.kind == "aggregate"):
                # The fusion layer sits between admission and the
                # workers: a fusable request joins its shape bucket
                # here (the host-side encode runs on THIS caller's
                # thread, so it parallelizes across tenants);
                # everything else falls through to the solo queue,
                # including anything offered while the fuser is
                # closing. Tune requests never fuse — the megasweep is
                # its own batched program.
                try:
                    routed = self._fuser.offer(pending)
                except Exception:
                    routed = False
            if verdict is None and not routed:
                with self._admit:
                    if self._closed.is_set():  # raced close()
                        verdict = ("shutdown",
                                   "service is draining; submit refused")
                    else:
                        try:
                            self._q.put_nowait(pending)
                        except queue.Full:  # raced another admitter
                            verdict = ("queue_full", full_detail)
            if verdict is not None:
                # Release BEFORE the rollback drops the id from _live —
                # see _release_lease for the dedup race this order
                # closes.
                self._release_lease(lease)
                self._rollback_admission(tenant, rid)
                obs_monitor.unregister_request(rid)
                return self._refuse(rid, tenant, *verdict)
            obs.inc("serve.requests_admitted")
            self._push_occupancy()
        pending.done.wait()
        kind, value = pending.outcome
        if kind == "raise":
            raise value
        return value

    def _check_rate(self, tenant: str,
                    cap: int) -> Optional[Tuple[str, str]]:
        """Per-tenant admission-rate quota, evaluated (and recorded)
        under the admission lock: a sliding one-second window of prior
        admissions on the injectable clock. Refused attempts do not
        count toward the window — a refused client retrying is not
        admitted traffic."""
        now = self._clock.monotonic()
        times = self._admit_times.get(tenant)
        if times:
            cutoff = now - _RATE_WINDOW_S
            while times and times[0] <= cutoff:
                times.pop(0)
            if len(times) >= cap:
                return ("quota",
                        f"tenant '{tenant}' exceeded its rate quota "
                        f"of {cap} request(s)/s; back off and "
                        "resubmit")
        return None

    def _rollback_admission(self, tenant: str, rid: str) -> None:
        """Undo a tentative admission: give back the in-flight slot,
        the live request id AND the rate-window slot — a request later
        refused (overdraw, queue race, shutdown race) was never
        admitted traffic, so it must not eat into the tenant's rate
        quota (the _check_rate contract)."""
        with self._admit:
            self._inflight[tenant] = max(
                0, self._inflight.get(tenant, 0) - 1)
            self._live.discard((tenant, rid))
            if self._tenant_quota(tenant, "reqs_per_s",
                                  self.max_reqs_per_s) > 0:
                times = self._admit_times.get(tenant)
                if times:
                    times.pop()

    def _release_lease(self, lease: BudgetLease) -> None:
        """Refund a reserve that failed cleanly before any DP output
        existed — unless the lease is a restart replay, whose
        pre-death attempt may have drawn noise: that debit stays
        spent. Every caller MUST invoke this BEFORE removing the id
        from ``_live``: released first, a same-id retry arriving in
        between sees a 'released' debit and reserves fresh; removed
        first, the retry would dedup onto the still-'reserved' debit
        as a replayed lease whose budget this refund then yanks away.
        Tune leases are synthetic (zero amounts, never on disk):
        nothing to refund."""
        if lease.replayed or lease.state == "tune":
            return
        from pipelinedp_tpu import obs
        try:
            self.budgets.release(lease.tenant, lease.request_id)
        except Exception:
            obs.event("serve.release_failed",
                      request_id=lease.request_id, tenant=lease.tenant)
        self._push_tenant_state()

    def _refuse(self, rid: str, tenant: str, reason: str, detail: str,
                remaining: Optional[Budget] = None) -> Refusal:
        from pipelinedp_tpu import obs
        obs.inc("serve.requests_refused")
        obs.inc(f"serve.refusals.{reason}")
        obs.event("serve.refusal", request_id=rid, tenant=str(tenant),
                  reason=reason, detail=detail)
        refusal = Refusal(request_id=rid, tenant=str(tenant),
                          reason=reason, detail=detail,
                          remaining=remaining)
        # Books only for tenants that exist: refusals naming garbage
        # tenants must not grow directories/stores without bound.
        if self.budgets.has_tenant(str(tenant)):
            self._append_books(str(tenant), "serve.refusal", {
                "request_id": rid, "reason": reason, "detail": detail})
        return refusal

    # --- the workers ---

    def _make_teardown(self, pending: "_Pending"):
        def _teardown():
            with self._admit:
                tenant = pending.request.tenant
                self._inflight[tenant] = max(
                    0, self._inflight.get(tenant, 0) - 1)
                self._live.discard((tenant,
                                    pending.lease.request_id))
        return _teardown

    def _worker_loop(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=_POLL_S)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            # A queue item is one pending OR a whole fused batch
            # (serve/fusion.FusedBatch): the worker serves either as a
            # unit, but every member keeps its own teardown/finish —
            # leases resolve exactly once per request, batch or not.
            fused = hasattr(item, "entries")
            pendings = item.entries if fused else [item]
            for pending in pendings:
                pending.teardown = self._make_teardown(pending)
            try:
                if fused:
                    # Per-member contexts are restored inside the
                    # fused executor — one batch carries many traces.
                    self._fuser.execute(item)
                else:
                    # Explicit context handoff: contextvars never flow
                    # into this worker thread on their own.
                    with trace_context.restore(item.ctx):
                        self._execute(item)
            except BaseException as e:  # safety net: a worker must
                # never die holding an unfinished pending — the
                # submitter would block forever and the pool would
                # shrink. Surface the failure on the caller instead.
                for pending in pendings:
                    if not pending.done.is_set():
                        pending.finish("raise", e)
            finally:
                # finish() ran the teardown before unblocking the
                # submitter; this residual only fires if the execution
                # somehow exited without ever finishing a pending.
                for pending in pendings:
                    teardown, pending.teardown = pending.teardown, None
                    if teardown is not None:
                        teardown()

    def _warm_entry(self, request: ServeRequest,
                    signature: str) -> Tuple[_WarmEntry, bool]:
        key = (request.tenant, signature)
        with self._registry_lock:
            entry = self._registry.get(key)
            if entry is not None:
                entry.hits += 1
                return entry, True
        # Build outside the registry lock (backend construction may
        # probe); last writer wins on a same-key race — both entries
        # work, one simply stays cold.
        backend = self._backend_factory(request)
        engine = DPEngine(None, backend)
        entry = _WarmEntry(engine, backend)
        with self._registry_lock:
            self._registry.setdefault(key, entry)
            return self._registry[key], False

    def _drop_entry(self, request: ServeRequest, signature: str) -> None:
        """A failed request may leave its engine holding a half-run
        accountant; drop the slot so the next request rebuilds clean."""
        with self._registry_lock:
            self._registry.pop((request.tenant, signature), None)

    def _execute(self, pending: _Pending) -> None:
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import audit as obs_audit
        from pipelinedp_tpu.obs import monitor as obs_monitor
        from pipelinedp_tpu.resilience import faults
        request, lease = pending.request, pending.lease
        rid, tenant = lease.request_id, lease.tenant
        signature = params_signature(request)
        obs_monitor.update_request(rid, phase="running",
                                   signature=signature)
        if request.kind == "tune":
            self._execute_tune(pending, signature)
            return
        try:
            # The injected hard-kill seam: between the durable reserve
            # and any commit/release — a FaultInjected here models the
            # process dying mid-request, so the reserve MUST stand.
            faults.check_serve_request(pending.seq)
            entry, warm = self._warm_entry(request, signature)
            obs.inc("serve.warm_hits" if warm else "serve.cold_builds")
            with entry.lock:
                try:
                    # Per-request noise state on the resident backend:
                    # the engine reads ``backend.rng_seed`` at
                    # aggregate time, and the entry lock serializes
                    # same-key requests, so each request's noise
                    # stream is its own while the compiled program
                    # stays shared.
                    if hasattr(entry.backend, "rng_seed"):
                        entry.backend.rng_seed = request.rng_seed
                    accountant = NaiveBudgetAccountant(
                        total_epsilon=lease.epsilon,
                        total_delta=lease.delta)
                    accountant.bind_books(tenant, rid)
                    entry.engine.rebind_budget_accountant(accountant)
                    extractors = (request.data_extractors
                                  if request.data_extractors is not None
                                  else DataExtractors())
                    with obs_audit.books_context(tenant, rid):
                        with self._tr.span("serve.request", cat="serve",
                                           tenant=tenant,
                                           warm=warm) as sp:
                            result = entry.engine.aggregate(
                                request.dataset, request.params,
                                extractors,
                                public_partitions=(
                                    request.public_partitions))
                            accountant.compute_budgets()
                            results = list(result)
                except BaseException:
                    # Heal BEFORE the lock releases: a same-signature
                    # waiter may already hold this entry (fetched
                    # before the failure dropped it from the registry)
                    # and must rebind a fresh accountant, not be
                    # refused over this request's half-run one.
                    entry.engine.clear_budget_accountant()
                    raise
        except faults.FaultInjected as e:
            # Hard kill: do NOT release — noise may have been drawn.
            # The submitting caller sees the crash; the durable ledger
            # keeps the reserved debit, exactly what a real process
            # death leaves behind. The warm slot IS dropped: its engine
            # may hold a half-run accountant that would spuriously
            # refuse the next same-signature request.
            self._drop_entry(request, signature)
            obs.inc("serve.requests_killed")
            obs.event("serve.request_killed", request_id=rid,
                      tenant=tenant, error=repr(e))
            obs_monitor.unregister_request(rid)
            pending.finish("raise", e)
            return
        except Exception as e:
            # Clean failure before any DP release: refund the reserve
            # and refuse with the error — the engine slot is dropped
            # so half-run accountant state cannot leak into the next
            # request. A REPLAYED lease is the exception: its
            # pre-restart attempt may have drawn noise, so the debit
            # stays spent even though this attempt failed cleanly.
            self._drop_entry(request, signature)
            self._release_lease(lease)
            obs_monitor.unregister_request(rid)
            pending.finish("refusal", self._refuse(
                rid, tenant, "error",
                f"{type(e).__name__}: {e}"))
            return
        self._commit_and_respond(pending, accountant, results, warm,
                                 signature, sp.duration)

    def _commit_and_respond(self, pending: "_Pending", accountant,
                            results, warm: bool, signature: str,
                            wall_s: float, fused: bool = False) -> None:
        """The post-compute tail shared by the solo worker and the
        fused-batch executor: commit the durable debit, read the
        remaining budget, snapshot the audit record, append the books
        entry, unblock the submitter. The DP output exists by now, so
        a bookkeeping failure surfaces on the CALLER with the reserve
        left standing — refunding would be the unsafe direction.
        Restores the request's context itself: the fused executor
        reaches here on the fuser/worker thread with a DIFFERENT
        member's context (or none) bound."""
        with trace_context.restore(pending.ctx):
            self._commit_and_respond_bound(pending, accountant, results,
                                           warm, signature, wall_s,
                                           fused)

    def _commit_and_respond_bound(self, pending: "_Pending", accountant,
                                  results, warm: bool, signature: str,
                                  wall_s: float, fused: bool) -> None:
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        lease = pending.lease
        rid, tenant = lease.request_id, lease.tenant
        try:
            # The host release tail, as its own span: the last hop of
            # the request's causal chain (admit -> execute -> commit).
            with self._tr.span("serve.commit", cat="serve",
                               tenant=tenant):
                self.budgets.commit(tenant, rid)
                remaining = self.budgets.remaining(tenant)
                audit_record = accountant.audit_record()
        except Exception as e:
            obs.event("serve.commit_failed", request_id=rid,
                      tenant=tenant, error=repr(e))
            obs_monitor.unregister_request(rid)
            pending.finish("raise", e)
            return
        books = {
            "request_id": rid,
            "signature": signature,
            "warm": warm,
            "wall_s": round(wall_s, 6),
            "partitions_released": len(results),
            "epsilon": lease.epsilon,
            "delta": lease.delta,
            "remaining_epsilon": remaining.epsilon,
            "remaining_delta": remaining.delta,
            "audit": audit_record,
        }
        if fused:
            books["fused"] = True
        if pending.ctx is not None:
            # The durable half of the causal chain: store --summarize
            # --trace-id surfaces this books entry in the tree.
            books["trace_id"] = pending.ctx.trace_id
        self._append_books(tenant, "serve.request", books)
        if pending.ctx is not None and self._tr.recording:
            # Flush the commit span itself to the obs store: the
            # engine's run-report delta was appended BEFORE the span
            # above closed, so without this tail append the durable
            # chain would stop at the release — one cursor-delta entry
            # completes admission-through-commit for --trace-id.
            from pipelinedp_tpu.obs import store as obs_store
            obs_store.maybe_append_run_report("serve.commit")
        obs.inc("serve.requests_served")
        obs.metrics.observe(
            "serve.request_seconds", wall_s,
            help="end-to-end serve request wall seconds")
        self._push_tenant_state()
        self._push_occupancy()
        obs_monitor.unregister_request(rid)
        pending.finish("response", ServeResponse(
            request_id=rid, tenant=tenant, results=results,
            remaining=remaining, warm=warm, signature=signature,
            wall_s=wall_s, audit=audit_record,
            trace_id=(pending.ctx.trace_id
                      if pending.ctx is not None else None)))

    def _execute_tune(self, pending: "_Pending", signature: str) -> None:
        """Serve one ``kind="tune"`` request: contribution histograms +
        the utility-analysis megasweep + argmin over the batched error
        surface, on the warm (tenant, signature) backend. The sweep
        releases error estimates of hypothetical mechanisms, never
        private data, so the synthetic lease debits zero (ε, δ) — but
        the request is still books-stamped like any other. A second
        same-signature tune reuses the warm backend and the
        module-level jitted sweep kernels: zero new compile.program
        captures."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        from pipelinedp_tpu.resilience import faults
        request, lease = pending.request, pending.lease
        rid, tenant = lease.request_id, lease.tenant
        try:
            # Same hard-kill seam as aggregate execution; with no
            # reserve outstanding there is nothing durable to protect,
            # but the caller must still see the crash.
            faults.check_serve_request(pending.seq)
            entry, warm = self._warm_entry(request, signature)
            obs.inc("serve.warm_hits" if warm else "serve.cold_builds")
            with entry.lock:
                from pipelinedp_tpu.analysis import jax_sweep
                from pipelinedp_tpu.analysis import parameter_tuning
                extractors = (request.data_extractors
                              if request.data_extractors is not None
                              else DataExtractors())
                to_tune = request.tune_parameters
                if to_tune is None:
                    metric = request.params.metrics[0]
                    to_tune = parameter_tuning.ParametersToTune(
                        max_partitions_contributed=True,
                        max_contributions_per_partition=(
                            metric == Metrics.COUNT))
                tune_options = parameter_tuning.TuneOptions(
                    epsilon=float(request.epsilon),
                    delta=float(request.delta),
                    aggregate_params=request.params,
                    function_to_minimize=(
                        parameter_tuning.MinimizingFunction
                        .ABSOLUTE_ERROR),
                    parameters_to_tune=to_tune)
                with self._tr.span("serve.request", cat="serve",
                                   tenant=tenant, warm=warm,
                                   kind="tune") as sp:
                    hist = list(jax_sweep.fused_dataset_histograms(
                        request.dataset, extractors))[0]
                    tuned = parameter_tuning.tune(
                        request.dataset, entry.backend, hist,
                        tune_options, extractors,
                        request.public_partitions)
                    tune_result = list(tuned)[0]
        except faults.FaultInjected as e:
            # Hard kill mid-tune: no reserve to preserve (tune debits
            # nothing), but the warm slot is dropped and the caller
            # sees the crash, mirroring the aggregate path.
            self._drop_entry(request, signature)
            obs.inc("serve.requests_killed")
            obs.event("serve.request_killed", request_id=rid,
                      tenant=tenant, error=repr(e))
            obs_monitor.unregister_request(rid)
            pending.finish("raise", e)
            return
        except Exception as e:
            self._drop_entry(request, signature)
            self._release_lease(lease)  # no-op for a tune lease
            obs_monitor.unregister_request(rid)
            pending.finish("refusal", self._refuse(
                rid, tenant, "error",
                f"{type(e).__name__}: {e}"))
            return
        self._respond_tune(pending, tune_result, warm, signature,
                           sp.duration)

    def _respond_tune(self, pending: "_Pending", tune_result, warm: bool,
                      signature: str, wall_s: float) -> None:
        """The tune twin of ``_commit_and_respond``: there is no
        durable debit to commit — the lease was synthesized with zero
        (ε, δ) and never reserved — so the tail only stamps the books
        (with ``kind="tune"`` and ``budget_debited=False``) and hands
        the TuneResult back. ``remaining`` is read purely to show the
        caller their balance is untouched."""
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.obs import monitor as obs_monitor
        lease = pending.lease
        rid, tenant = lease.request_id, lease.tenant
        try:
            remaining = self.budgets.remaining(tenant)
        except Exception as e:
            obs.event("serve.commit_failed", request_id=rid,
                      tenant=tenant, error=repr(e))
            obs_monitor.unregister_request(rid)
            pending.finish("raise", e)
            return
        cfg = tune_result.utility_analysis_parameters
        best: Dict[str, Any] = {}
        if cfg.max_partitions_contributed is not None:
            best["max_partitions_contributed"] = int(
                cfg.max_partitions_contributed[tune_result.index_best])
        if cfg.max_contributions_per_partition is not None:
            best["max_contributions_per_partition"] = int(
                cfg.max_contributions_per_partition[
                    tune_result.index_best])
        audit_record = {
            "kind": "tune",
            "budget_debited": False,
            "simulated_epsilon": float(pending.request.epsilon),
            "simulated_delta": float(pending.request.delta),
            "candidates": int(cfg.size),
            "index_best": int(tune_result.index_best),
            "best": best,
        }
        books = {
            "request_id": rid,
            "signature": signature,
            "kind": "tune",
            "warm": warm,
            "wall_s": round(wall_s, 6),
            "candidates": int(cfg.size),
            "epsilon": 0.0,
            "delta": 0.0,
            "remaining_epsilon": remaining.epsilon,
            "remaining_delta": remaining.delta,
            "audit": audit_record,
        }
        if pending.ctx is not None:
            books["trace_id"] = pending.ctx.trace_id
        self._append_books(tenant, "serve.request", books)
        obs.inc("serve.requests_served")
        obs.inc("serve.tunes_served")
        obs.metrics.observe(
            "serve.request_seconds", wall_s,
            help="end-to-end serve request wall seconds")
        self._push_occupancy()
        obs_monitor.unregister_request(rid)
        pending.finish("response", ServeResponse(
            request_id=rid, tenant=tenant,
            results=[("tune", tune_result)],
            remaining=remaining, warm=warm, signature=signature,
            wall_s=wall_s, audit=audit_record,
            trace_id=(pending.ctx.trace_id
                      if pending.ctx is not None else None)))

    # --- the metrics plane (obs/metrics.py + heartbeat tenants) ---

    def _push_occupancy(self) -> None:
        """Serve occupancy gauges for ``/metrics``: queue depth,
        admitted-in-flight count, and fusion bucket fill. Pushed at
        admission and at every completion — cheap last-write-wins
        writes, recorded whether or not the endpoint is on (the
        always-on counter discipline)."""
        from pipelinedp_tpu.obs import metrics
        metrics.set_gauge("serve.queue_depth", float(self._q.qsize()),
                          help="serve queue depth (pendings + fused "
                          "batches)")
        with self._admit:
            inflight = sum(self._inflight.values())
        metrics.set_gauge("serve.inflight", float(inflight),
                          help="requests admitted and not yet finished")
        if self._fuser is not None:
            try:
                snap = self._fuser.snapshot()
            except Exception:
                return
            metrics.set_gauge("serve.fusion_queued",
                              float(snap.get("queued", 0)),
                              help="requests waiting in open fusion "
                              "windows")
            for label, b in (snap.get("buckets") or {}).items():
                metrics.set_gauge("serve.fusion_bucket_fill",
                                  float(b.get("queued", 0)),
                                  help="per-bucket fusion window fill",
                                  bucket=label)

    def _push_tenant_state(self) -> None:
        """Per-tenant budget gauges for ``/metrics`` plus the
        heartbeat's ``tenants`` section, both fed by the durable
        ledger's :meth:`TenantBudgetLedger.overview`. Burn rate is
        committed epsilon over service uptime on the injectable clock
        — the metrics plane never reads wall time itself. Never takes
        a request down."""
        from pipelinedp_tpu.obs import metrics
        from pipelinedp_tpu.obs import monitor as obs_monitor
        try:
            overview = self.budgets.overview()
        except Exception:
            return
        uptime = max(self._clock.monotonic() - self._t0, 1e-9)
        with self._admit:
            inflight = dict(self._inflight)
        tenants_hb: Dict[str, Any] = {}
        for tenant, info in overview.items():
            metrics.set_gauge("tenant.epsilon_remaining",
                              info["remaining_epsilon"],
                              help="tenant budget epsilon remaining",
                              tenant=tenant)
            metrics.set_gauge("tenant.delta_remaining",
                              info["remaining_delta"],
                              help="tenant budget delta remaining",
                              tenant=tenant)
            metrics.set_gauge("tenant.reserves_in_flight",
                              float(info["reserves_in_flight"]),
                              help="durable reserves neither committed "
                              "nor released",
                              tenant=tenant)
            metrics.set_gauge("tenant.epsilon_burn_per_s",
                              info["committed_epsilon"] / uptime,
                              help="committed epsilon per uptime second",
                              tenant=tenant)
            tenants_hb[tenant] = {
                "epsilon_remaining": info["remaining_epsilon"],
                "delta_remaining": info["remaining_delta"],
                "reserves_in_flight": info["reserves_in_flight"],
                "committed_epsilon": info["committed_epsilon"],
                "inflight": int(inflight.get(tenant, 0)),
            }
        obs_monitor.update_tenants(tenants_hb or None)

    # --- per-tenant books ---

    def books_dir(self, tenant: str) -> str:
        return os.path.join(self.ledger_dir, "books",
                            tenant_slug(tenant))

    def _append_books(self, tenant: str, name: str,
                      payload: Dict[str, Any]) -> None:
        """Append one entry to the tenant's own run-ledger store (the
        fsync'd JSONL appender — the store appends deltas linearly, so
        the books come for free). Never takes a request down."""
        try:
            from pipelinedp_tpu import obs
            from pipelinedp_tpu.obs.store import LedgerStore
            # Creation is serialized so each tenant gets exactly ONE
            # LedgerStore instance (the store's one-lock-per-file
            # contract); the append itself runs outside the lock —
            # the store has its own.
            with self._books_lock:
                store = self._books_stores.get(tenant)
                if store is None:
                    # lint: disable=blocking-under-lock(mkdir-only creation; the fsync'd append runs outside)
                    store = LedgerStore(self.books_dir(tenant))
                    self._books_stores[tenant] = store
                if self._env is None:
                    self._env = obs.environment_fingerprint()
                env = self._env
            store.append(name, {"serve": dict(payload, tenant=tenant)},
                         env=env)
        except Exception:
            pass
