"""Durable per-tenant privacy-budget ledgers for the resident service.

The batch path's two-phase ``BudgetAccountant`` is per-engine and
in-memory: its total (eps, delta) is born and dies with one process.
A resident multi-tenant service needs the OTHER half of the story —
how much of a tenant's lifetime budget is left across requests and
restarts. This module is that half:

* one JSON document per tenant (``budget-<slug>.json``), written with
  the checkpoint store's atomic discipline (tmp + fsync +
  ``os.replace`` via ``resilience.checkpoint.atomic_write_json``) so a
  kill at any instant leaves a consistent ledger;
* **two-phase debits**: ``reserve()`` durably records the request's
  (eps, delta) BEFORE any compute runs and refuses (raises
  :class:`Overdraw`) when the tenant's remaining budget cannot cover
  it; ``commit()`` marks the spend final after the release;
  ``release()`` refunds a reserve whose request failed cleanly before
  any DP output existed. A reserve that is neither committed nor
  released — the kill-mid-request window — STAYS SPENT on replay:
  noise may already have been drawn, and the conservative direction
  for privacy is to count it;
* **exactly-once** under concurrency and restarts: debits key on the
  request id — a second ``reserve()`` for the same id returns the
  existing lease instead of double-debiting, and per-tenant locks
  serialize the read-modify-write so two racing requests can never
  both fit into one remaining slice. The dedup lease exists for
  RESTART REPLAY (a retry of a request the dead process never
  finished); while the original is still live in-process, the serve
  layer refuses the duplicate at admission — handing the retry a
  lease there would let one charge release two noisy views.

The per-request accountant then simply takes the leased (eps, delta)
as its totals — the accountant by construction distributes exactly
what it was given, so ledger arithmetic and accountant arithmetic
agree to the float.

Budget-ledger writes are confined to this package (plus
``budget_accounting.py``) by ``make noserve`` and its AST twin in
``tests/test_serve.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
from typing import Any, Dict, Optional

from pipelinedp_tpu import input_validators
from pipelinedp_tpu.budget_accounting import Budget
from pipelinedp_tpu.resilience.checkpoint import (atomic_write_json,
                                                  read_json)

SCHEMA_VERSION = 1

#: Absolute slack for float comparisons on eps/delta sums: a tenant
#: whose debits sum to its total via a different addition order must
#: not be refused over the last ulp, and a genuine overdraw is never
#: this small in practice.
EPS_TOL = 1e-9
DELTA_TOL = 1e-15


class LedgerError(Exception):
    """Base class for budget-ledger failures."""


class UnknownTenant(LedgerError):
    """The tenant has no ledger in this directory."""


class TenantMismatch(LedgerError):
    """``open_tenant`` was asked to create a tenant whose durable
    ledger already exists with DIFFERENT totals — silently adopting
    either side would rewrite a privacy guarantee."""


class DuplicateRequest(LedgerError):
    """``reserve()`` was asked to re-reserve a request id whose debit
    is already COMMITTED — its DP output was released; running the
    request again would release a second noisy view of the data while
    charging the budget once."""


class Overdraw(LedgerError):
    """The request's (eps, delta) demand exceeds the tenant's
    remaining budget; carries the shortfall so the refusal can name
    it."""

    def __init__(self, tenant: str, request_id: str, requested: Budget,
                 remaining: Budget):
        self.tenant = tenant
        self.request_id = request_id
        self.requested = requested
        self.remaining = remaining
        self.shortfall = Budget(
            max(0.0, requested.epsilon - remaining.epsilon),
            max(0.0, requested.delta - remaining.delta))
        super().__init__(
            f"tenant '{tenant}' request '{request_id}' would overdraw "
            f"the budget ledger: requested {requested}, remaining "
            f"{remaining}, shortfall {self.shortfall}")


@dataclasses.dataclass(frozen=True)
class BudgetLease:
    """One granted reserve: the (eps, delta) a request may spend."""
    tenant: str
    request_id: str
    epsilon: float
    delta: float
    #: "reserved" on a fresh grant; the prior state when ``reserve``
    #: deduplicated an id it had already seen (exactly-once).
    state: str = "reserved"
    #: True when this lease dedups onto a debit reserved BEFORE this
    #: reserve call (restart replay). A replayed lease must NEVER be
    #: refunded on a clean failure: the ORIGINAL attempt may already
    #: have drawn noise before the process died, so the conservative
    #: direction is to leave the debit spent.
    replayed: bool = False


def tenant_slug(tenant: str) -> str:
    """Filesystem-safe, collision-resistant file stem for a tenant
    name (the name itself may hold any unicode)."""
    safe = "".join(c if (c.isalnum() or c in "-_") else "-"
                   for c in str(tenant))[:48]
    digest = hashlib.sha256(str(tenant).encode("utf-8")).hexdigest()[:8]
    return f"{safe}-{digest}"


class TenantBudgetLedger:
    """All tenants' durable budget ledgers under one directory.

    Thread-safe within a process (one lock per tenant). Cross-process
    writers must not share a directory concurrently — the intended
    deployment is one resident service process owning its ledger
    directory, with restarts (not concurrent peers) reading it back.
    """

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._tenant_locks: Dict[str, threading.Lock] = {}
        #: Write-through cache of each tenant's document; disk is the
        #: source of truth on first touch (restart replay).
        self._states: Dict[str, Dict[str, Any]] = {}

    # --- plumbing ---

    def path_for(self, tenant: str) -> str:
        return os.path.join(self.directory,
                            f"budget-{tenant_slug(tenant)}.json")

    def _tenant_lock(self, tenant: str) -> threading.Lock:
        with self._lock:
            lock = self._tenant_locks.get(tenant)
            if lock is None:
                lock = threading.Lock()
                self._tenant_locks[tenant] = lock
            return lock

    def _load(self, tenant: str) -> Optional[Dict[str, Any]]:
        """The tenant's document (cache, else disk replay); None when
        the tenant was never opened here. Caller holds the lock."""
        state = self._states.get(tenant)
        if state is None:
            state = read_json(self.path_for(tenant))
            if state is not None:
                self._states[tenant] = state
        return state

    def _write(self, tenant: str, state: Dict[str, Any]) -> None:
        """Durably write ``state``, then install it as the cached
        document. Callers pass a NEW doc (never the cached one mutated
        in place), so a failed write — disk full, I/O error — leaves
        the cache on the last durable doc and memory never diverges
        from disk."""
        atomic_write_json(self.path_for(tenant), state)
        self._states[tenant] = state

    @staticmethod
    def _spent(state: Dict[str, Any]) -> Budget:
        """Sum of all debits that count as spent: reserved AND
        committed (a reserve whose request may have drawn noise is
        spent until explicitly released)."""
        eps = delta = 0.0
        for d in state["debits"].values():
            if d["state"] in ("reserved", "committed"):
                eps += float(d["epsilon"])
                delta += float(d["delta"])
        return Budget(eps, delta)

    # --- public API ---

    def open_tenant(self, tenant: str, total_epsilon: float,
                    total_delta: float) -> Budget:
        """Create (or re-open after restart) a tenant's ledger and
        return its remaining budget. Idempotent for matching totals;
        raises :class:`TenantMismatch` when a durable ledger already
        records different ones."""
        input_validators.validate_epsilon_delta(total_epsilon, total_delta,
                                               "TenantBudgetLedger")
        with self._tenant_lock(tenant):
            state = self._load(tenant)
            if state is None:
                state = {"schema_version": SCHEMA_VERSION,
                         "tenant": str(tenant),
                         "total_epsilon": float(total_epsilon),
                         "total_delta": float(total_delta),
                         "debits": {}}
                self._write(tenant, state)
                from pipelinedp_tpu import obs
                obs.inc("serve.tenants_opened")
                obs.event("serve.tenant_opened", tenant=str(tenant),
                          path=self.path_for(tenant))
            elif (state["total_epsilon"] != float(total_epsilon) or
                  state["total_delta"] != float(total_delta)):
                raise TenantMismatch(
                    f"tenant '{tenant}' ledger at "
                    f"{self.path_for(tenant)} records totals "
                    f"(eps={state['total_epsilon']}, "
                    f"delta={state['total_delta']}), not "
                    f"(eps={total_epsilon}, delta={total_delta}) — "
                    "refusing to adopt either silently")
            return self._remaining_locked(state)

    def _remaining_locked(self, state: Dict[str, Any]) -> Budget:
        spent = self._spent(state)
        return Budget(state["total_epsilon"] - spent.epsilon,
                      state["total_delta"] - spent.delta)

    def has_tenant(self, tenant: str) -> bool:
        """Whether the tenant has a ledger here (cache or disk). An
        advisory, lock-free check: refusal bookkeeping uses it so
        garbage tenant names never grow books directories — or even
        per-tenant lock entries here."""
        return tenant in self._states or os.path.isfile(
            self.path_for(tenant))

    def remaining(self, tenant: str) -> Budget:
        """The tenant's remaining (eps, delta) — totals minus every
        reserved/committed debit, replayed from disk if needed."""
        with self._tenant_lock(tenant):
            state = self._load(tenant)
            if state is None:
                raise UnknownTenant(f"tenant '{tenant}' has no ledger "
                                    f"under {self.directory}")
            return self._remaining_locked(state)

    def debits(self, tenant: str) -> Dict[str, Dict[str, Any]]:
        """Snapshot of the tenant's per-request debit map."""
        with self._tenant_lock(tenant):
            state = self._load(tenant)
            if state is None:
                raise UnknownTenant(f"tenant '{tenant}' has no ledger "
                                    f"under {self.directory}")
            return {k: dict(v) for k, v in state["debits"].items()}

    def overview(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant budget overview across every tenant this process
        has loaded (the write-through cache is exactly that set —
        restart replay loads a tenant on first touch): totals,
        remaining (eps, delta), committed spend, and reserves still in
        flight. Read-only — the material behind the heartbeat's
        ``tenants`` section and the ``/metrics`` per-tenant gauges."""
        with self._lock:
            tenants = sorted(self._states)
        out: Dict[str, Dict[str, Any]] = {}
        for tenant in tenants:
            with self._tenant_lock(tenant):
                state = self._load(tenant)
                if state is None:
                    continue
                reserved_n = 0
                reserved_eps = reserved_delta = 0.0
                committed_eps = committed_delta = 0.0
                for d in state["debits"].values():
                    if d["state"] == "reserved":
                        reserved_n += 1
                        reserved_eps += float(d["epsilon"])
                        reserved_delta += float(d["delta"])
                    elif d["state"] == "committed":
                        committed_eps += float(d["epsilon"])
                        committed_delta += float(d["delta"])
                remaining = self._remaining_locked(state)
                out[tenant] = {
                    "total_epsilon": float(state["total_epsilon"]),
                    "total_delta": float(state["total_delta"]),
                    "remaining_epsilon": remaining.epsilon,
                    "remaining_delta": remaining.delta,
                    "committed_epsilon": committed_eps,
                    "committed_delta": committed_delta,
                    "reserves_in_flight": reserved_n,
                    "reserved_epsilon": reserved_eps,
                    "reserved_delta": reserved_delta,
                }
        return out

    def reserve(self, tenant: str, request_id: str, epsilon: float,
                delta: float) -> BudgetLease:
        """Durably debit (eps, delta) for ``request_id`` BEFORE any
        compute runs. Exactly-once: an id already debited returns its
        existing lease unchanged. Raises :class:`Overdraw` (with the
        shortfall) without writing anything when the remaining budget
        cannot cover the demand."""
        from pipelinedp_tpu import obs
        if not (epsilon > 0):
            raise ValueError(f"request epsilon must be positive, got "
                             f"{epsilon}")
        if delta < 0:
            raise ValueError(f"request delta must be >= 0, got {delta}")
        with self._tenant_lock(tenant):
            state = self._load(tenant)
            if state is None:
                raise UnknownTenant(f"tenant '{tenant}' has no ledger "
                                    f"under {self.directory}")
            existing = state["debits"].get(str(request_id))
            if existing is not None and existing["state"] == "reserved":
                # Exactly-once restart replay: the debit already
                # happened before a restart (or kill) took the request
                # down mid-compute; hand back the same lease. The
                # serve layer refuses an id whose original is still
                # live IN-PROCESS before ever reaching here. A retry
                # that wants bit-identical replay must carry a fixed
                # rng_seed — the same discipline the checkpoint store
                # documents.
                if (float(existing["epsilon"]) != float(epsilon) or
                        float(existing["delta"]) != float(delta)):
                    # A replay must carry the ORIGINAL demand: handing
                    # the old lease to a retry that asked for different
                    # amounts would silently run it under amounts the
                    # caller never requested.
                    raise LedgerError(
                        f"tenant '{tenant}' request '{request_id}' is "
                        f"already reserved at (eps="
                        f"{existing['epsilon']}, delta="
                        f"{existing['delta']}); a replay retry must "
                        f"carry those amounts, not (eps={epsilon}, "
                        f"delta={delta}) — use a fresh request id for "
                        "a different demand")
                obs.inc("serve.budget_reserve_dedups")
                return BudgetLease(tenant=str(tenant),
                                   request_id=str(request_id),
                                   epsilon=float(existing["epsilon"]),
                                   delta=float(existing["delta"]),
                                   state=str(existing["state"]),
                                   replayed=True)
            if existing is not None and existing["state"] == "committed":
                # The id's output was already RELEASED: re-running it
                # would publish a second noisy view on one charge.
                obs.inc("serve.budget_duplicate_refusals")
                raise DuplicateRequest(
                    f"tenant '{tenant}' request '{request_id}' is "
                    "already committed — its DP output was released; "
                    "a re-run needs a fresh request id (and fresh "
                    "budget)")
            # A "released" debit was refunded (clean pre-release
            # failure): a retry is a fresh debit — fall through to the
            # overdraw check and overwrite it with the new amounts.
            remaining = self._remaining_locked(state)
            if (epsilon > remaining.epsilon + EPS_TOL or
                    delta > remaining.delta + DELTA_TOL):
                obs.inc("serve.budget_overdraw_refusals")
                obs.event("serve.budget_overdraw", tenant=str(tenant),
                          request_id=str(request_id),
                          requested_eps=float(epsilon),
                          requested_delta=float(delta),
                          remaining_eps=remaining.epsilon,
                          remaining_delta=remaining.delta)
                raise Overdraw(str(tenant), str(request_id),
                               Budget(float(epsilon), float(delta)),
                               remaining)
            # Copy-on-write: mutate a fresh doc so a failed durable
            # write leaves the cached doc untouched (see _write).
            debits = {k: dict(v) for k, v in state["debits"].items()}
            debits[str(request_id)] = {
                "epsilon": float(epsilon), "delta": float(delta),
                "state": "reserved"}
            self._write(tenant, dict(state, debits=debits))
            obs.inc("serve.budget_reserves")
            return BudgetLease(tenant=str(tenant),
                               request_id=str(request_id),
                               epsilon=float(epsilon),
                               delta=float(delta))

    def _transition(self, tenant: str, request_id: str,
                    new_state: str) -> None:
        with self._tenant_lock(tenant):
            state = self._load(tenant)
            if state is None:
                raise UnknownTenant(f"tenant '{tenant}' has no ledger "
                                    f"under {self.directory}")
            debit = state["debits"].get(str(request_id))
            if debit is None:
                raise LedgerError(
                    f"tenant '{tenant}' has no debit for request "
                    f"'{request_id}'")
            if debit["state"] == new_state:
                return  # idempotent replay
            if debit["state"] != "reserved":
                raise LedgerError(
                    f"debit '{request_id}' is {debit['state']}, cannot "
                    f"move to {new_state} (only a reserve can)")
            # Copy-on-write: mutate a fresh doc so a failed durable
            # write leaves the cached doc untouched (see _write).
            debits = {k: dict(v) for k, v in state["debits"].items()}
            debits[str(request_id)]["state"] = new_state
            self._write(tenant, dict(state, debits=debits))

    def commit(self, tenant: str, request_id: str) -> None:
        """Mark a reserve final — the request's DP output was released."""
        self._transition(tenant, request_id, "committed")
        from pipelinedp_tpu import obs
        obs.inc("serve.budget_commits")

    def release(self, tenant: str, request_id: str) -> None:
        """Refund a reserve whose request failed CLEANLY before any DP
        output (or noise) existed. Never call this on a kill path —
        a request that may have drawn noise stays spent — nor for a
        lease ``reserve()`` handed back with ``replayed=True``: the
        pre-restart attempt may have drawn noise before dying."""
        self._transition(tenant, request_id, "released")
        from pipelinedp_tpu import obs
        obs.inc("serve.budget_releases")
