"""Execution backends. The host backends live in
``pipelinedp_tpu.pipeline_backend``; this package holds the TPU plane."""

from pipelinedp_tpu.backends.jax_backend import JaxBackend
