"""JaxBackend — the TPU-native execution plane.

For standard aggregations (COUNT / PRIVACY_ID_COUNT / SUM / MEAN /
VARIANCE / VECTOR_SUM) the engine bypasses the op-by-op graph entirely and
lowers to the fused XLA program in ``pipelinedp_tpu.jax_engine`` (one
device program for bounding + combine + selection + noise). Everything
else (percentiles, custom combiners, the analysis graphs, arbitrary user
``map``s) falls back to the host generator semantics inherited from
``LocalBackend`` — correctness everywhere, compiled speed on the hot
path.

Multi-chip execution goes through ``pipelinedp_tpu.parallel`` (shard rows
over a ``jax.sharding.Mesh``, per-shard segment reduction, ``psum`` for
the per-partition accumulator exchange); construct the backend with a
mesh to enable it.
"""

from __future__ import annotations

from typing import Optional

from pipelinedp_tpu.pipeline_backend import LocalBackend


class JaxBackend(LocalBackend):
    """Marker + host-fallback backend for the fused JAX plane.

    Attributes:
      mesh: optional ``jax.sharding.Mesh`` for multi-chip runs (rows are
        sharded by privacy id over the first mesh axis).
      rng_seed: optional fixed seed for reproducible runs (tests).
    """

    supports_fused_aggregation = True

    def __init__(self, mesh=None, rng_seed: Optional[int] = None):
        self.mesh = mesh
        self.rng_seed = rng_seed
