"""JaxBackend — the TPU-native execution plane.

For standard aggregations (COUNT / PRIVACY_ID_COUNT / SUM / MEAN /
VARIANCE / VECTOR_SUM) the engine bypasses the op-by-op graph entirely and
lowers to the fused XLA program in ``pipelinedp_tpu.jax_engine`` (one
device program for bounding + combine + selection + noise). Everything
else (percentiles, custom combiners, the analysis graphs, arbitrary user
``map``s) falls back to the host generator semantics inherited from
``LocalBackend`` — correctness everywhere, compiled speed on the hot
path.

Multi-chip execution goes through ``pipelinedp_tpu.parallel`` (shard rows
over a ``jax.sharding.Mesh``, per-shard segment reduction, ``psum`` for
the per-partition accumulator exchange); construct the backend with a
mesh to enable it.

Fault tolerance goes through ``pipelinedp_tpu.resilience``: pass
``health_policy`` to probe the accelerator (with bounded retry +
backoff) before the first kernel and degrade to CPU — flagged on
``backend.degraded``, never silently — when the runtime is wedged; pass
``checkpoint`` (a path or ``CheckpointStore``) to persist streamed
per-chunk state so a killed run resumes bit-identically without
re-drawing noise (requires ``rng_seed``).
"""

from __future__ import annotations

from typing import Optional

from pipelinedp_tpu.pipeline_backend import LocalBackend


class JaxBackend(LocalBackend):
    """Marker + host-fallback backend for the fused JAX plane.

    Attributes:
      mesh: optional ``jax.sharding.Mesh`` for multi-chip runs (rows are
        sharded by privacy id over the first mesh axis).
      rng_seed: optional fixed seed for reproducible runs (tests,
        checkpointed runs).
      checkpoint: optional checkpoint path or
        ``resilience.checkpoint.CheckpointStore`` — enables budget-safe
        resume of streamed aggregations.
      degraded: True when the device-health probe exhausted its retries
        and execution fell back to CPU. Results produced in this mode
        must be flagged by callers (bench emits ``"degraded": true``).
      health: the ``resilience.health.HealthReport`` of the probe, or
        None when no ``health_policy`` was requested.
      ingest_executor: overlapped streaming-ingest executor
        (``pipelinedp_tpu/ingest``): True/False force it on/off, None
        (default) follows ``PIPELINEDP_TPU_INGEST_EXECUTOR`` (on unless
        0). Both modes are bit-identical; off = the serial reference
        path.
      stream_cache: per-device HBM budget (bytes) for keeping streamed
        batches device-resident so percentile pass B re-reads them from
        HBM instead of re-shipping over the host link. None (default)
        follows ``PIPELINEDP_TPU_STREAM_CACHE`` (4 GiB); 0 disables.
        The cache is a PREFIX cache: on overflow the cached batch
        prefix stays resident and only the suffix re-ships each pass-B
        sweep (``pass_b_source: "hybrid"``). All three sources —
        device_cache / hybrid / reship — are bit-identical.

    Constructing the backend also wires JAX's persistent compilation
    cache when ``PIPELINEDP_TPU_COMPILE_CACHE`` names a directory, so
    cold processes skip XLA recompilation of the fused kernels.
    """

    supports_fused_aggregation = True

    def __init__(self, mesh=None, rng_seed: Optional[int] = None,
                 checkpoint=None, health_policy=None, clock=None,
                 probe_timeout_s: Optional[float] = None,
                 ingest_executor: Optional[bool] = None,
                 stream_cache: Optional[int] = None):
        import os

        from pipelinedp_tpu.ingest import maybe_enable_compile_cache
        from pipelinedp_tpu.resilience.health import DEGRADED_ENV

        maybe_enable_compile_cache()
        self.mesh = mesh
        self.rng_seed = rng_seed
        self.checkpoint = checkpoint
        self.ingest_executor = ingest_executor
        self.stream_cache = stream_cache
        # A prior degradation in this process pinned the platform to
        # CPU for EVERY later backend — the flag must say so even when
        # this construction ran no probe of its own.
        self.degraded = bool(os.environ.get(DEGRADED_ENV))
        self.health = None
        if health_policy is not None:
            from pipelinedp_tpu.resilience import health as _health
            policy = (None if health_policy is True else health_policy)
            self.health = _health.ensure_device_or_degrade(
                policy=policy, clock=clock, timeout_s=probe_timeout_s)
            self.degraded = self.health.degraded
            if self.degraded:
                # A wedged-device mesh is unusable; the CPU fallback
                # runs single-device. NEVER silent: ``degraded`` says so.
                self.mesh = None
        from pipelinedp_tpu import obs
        # seed_fixed, never the seed itself: run reports are meant to
        # be shared, and noise draws are pure functions of the seed —
        # publishing it would let a report holder subtract the noise.
        obs.event("backend.created", degraded=self.degraded,
                  mesh_devices=(int(self.mesh.devices.size)
                                if self.mesh is not None else 0),
                  seed_fixed=rng_seed is not None,
                  checkpoint=bool(checkpoint))
