"""DP noise math — count/sum/mean/variance/vector-sum computations.

Capability parity with the reference's ``pipeline_dp/dp_computations.py``
(sensitivity calculus :72-91, count :255, sum :278, the normalized-sum mean
trick :310-397, variance :400-459, vector noise :178-222, budget splitting
:224-252, noise-std predictors :462-489) with one deliberate re-design for
TPU: **every compute function is vectorized** — inputs may be Python scalars
or NumPy arrays of per-partition aggregates, and one call draws one batched
noise sample for *all* partitions. The scalar path (used by the host
combiners) is just the 0-d case. The fused XLA program reuses the same
calibration helpers (which are pure host arithmetic) and swaps the NumPy
samplers for ``jax.random`` ones.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import numpy as np

from pipelinedp_tpu.aggregate_params import NoiseKind, NormKind
from pipelinedp_tpu.ops import noise as noise_ops

ArrayLike = Union[float, int, np.ndarray]

# Re-exported calibration helpers (reference :72-108).
compute_l1_sensitivity = noise_ops.compute_l1_sensitivity
compute_l2_sensitivity = noise_ops.compute_l2_sensitivity
compute_sigma = noise_ops.compute_sigma


def count_sensitivity_pair(max_partitions_contributed,
                           max_contributions_per_partition,
                           max_contributions):
    """(l0, linf) for count-like releases, shared by the host mechanisms
    and the fused plane's noise calibration. Total-cap mode: a unit's M
    rows can all land in ONE partition, so the L2-worst case is
    concentration — (1, M) yields Delta1 = Delta2 = M, valid for both
    mechanisms."""
    if max_contributions is not None:
        return 1.0, float(max_contributions)
    return float(max_partitions_contributed), float(
        max_contributions_per_partition)


def pid_count_sensitivity_pair(max_partitions_contributed,
                               max_contributions_per_partition,
                               max_contributions):
    """(l0, linf) for the privacy-id count: a unit adds at most 1 per
    touched partition, so concentration cannot occur — total-cap mode
    gets the tight (M, 1) with Delta2 = sqrt(M). Pair mode keeps the
    reference's (l0, linf) exactly (conservative when linf > 1,
    reference ``combiners.py:211-239``)."""
    if max_contributions is not None:
        return float(max_contributions), 1.0
    return float(max_partitions_contributed), float(
        max_contributions_per_partition)


def compute_middle(min_value: float, max_value: float) -> float:
    """Midpoint, written to avoid overflow on large bounds (reference :65)."""
    return min_value + (max_value - min_value) / 2


def compute_squares_interval(min_value: float,
                             max_value: float) -> Tuple[float, float]:
    """Bounds of {x^2 : x in [min, max]} (reference :58)."""
    if min_value < 0 < max_value:
        return 0, max(min_value**2, max_value**2)
    return min_value**2, max_value**2


@dataclasses.dataclass
class ScalarNoiseParams:
    """Parameters of scalar DP aggregations (reference :23-55).

    Contribution bounding comes in two modes: the (l0, linf) pair
    (``max_partitions_contributed`` x ``max_contributions_per_partition``)
    or a single total cap ``max_contributions`` across all partitions —
    a parameter the reference declares end-to-end but never implements
    (its engine raises, reference ``dp_engine.py:395-396``). Here the
    total-cap mode is fully supported; see ``count_sensitivities`` /
    ``pid_count_sensitivities`` / ``sum_sensitivities`` for the
    calculus."""
    eps: float
    delta: float
    min_value: Optional[float]
    max_value: Optional[float]
    min_sum_per_partition: Optional[float]
    max_sum_per_partition: Optional[float]
    max_partitions_contributed: Optional[int]
    max_contributions_per_partition: Optional[int]
    noise_kind: NoiseKind
    max_contributions: Optional[int] = None

    def __post_init__(self):
        assert (self.min_value is None) == (self.max_value is None), (
            "min_value and max_value should both be set or both be None.")
        assert (self.min_sum_per_partition is None) == (
            self.max_sum_per_partition is None), (
                "min_sum_per_partition and max_sum_per_partition should both "
                "be set or both be None.")
        assert (self.max_contributions is not None or
                self.max_partitions_contributed is not None), (
            "either max_contributions or max_partitions_contributed "
            "must be set")

    def l0_sensitivity(self) -> int:
        if self.max_contributions is not None:
            # A privacy unit touches at most max_contributions partitions.
            return self.max_contributions
        return self.max_partitions_contributed

    def count_sensitivities(self):
        """(l0, linf) for count-like releases — see
        :func:`count_sensitivity_pair`."""
        return count_sensitivity_pair(self.max_partitions_contributed,
                                      self.max_contributions_per_partition,
                                      self.max_contributions)

    def pid_count_sensitivities(self):
        """(l0, linf) for the privacy-id count — see
        :func:`pid_count_sensitivity_pair`."""
        return pid_count_sensitivity_pair(
            self.max_partitions_contributed,
            self.max_contributions_per_partition, self.max_contributions)

    def sum_sensitivities(self):
        """(l0, linf) for the SUM release in either clipping mode: with
        per-contribution value bounds, linf scales the count-like pair by
        max|bound|; with per-partition sum bounds, each touched
        partition's sum is capped directly."""
        if self.bounds_per_contribution_are_set:
            max_abs = max(abs(self.min_value), abs(self.max_value))
            l0, linf = self.count_sensitivities()
            return l0, linf * max_abs
        return float(self.l0_sensitivity()), max(
            abs(self.min_sum_per_partition),
            abs(self.max_sum_per_partition))

    @property
    def bounds_per_contribution_are_set(self) -> bool:
        return self.min_value is not None and self.max_value is not None

    @property
    def bounds_per_partition_are_set(self) -> bool:
        return (self.min_sum_per_partition is not None and
                self.max_sum_per_partition is not None)


def _noise_std(eps: float, delta: float, l0_sensitivity: float,
               linf_sensitivity: float, noise_kind: NoiseKind) -> float:
    """Standard deviation of the calibrated additive noise."""
    if noise_kind == NoiseKind.LAPLACE:
        return noise_ops.laplace_std(
            eps, compute_l1_sensitivity(l0_sensitivity, linf_sensitivity))
    if noise_kind == NoiseKind.GAUSSIAN:
        return noise_ops.gaussian_sigma(
            eps, delta, compute_l2_sensitivity(l0_sensitivity,
                                               linf_sensitivity))
    raise ValueError("Noise kind must be either Laplace or Gaussian.")


def _secure_release(value: ArrayLike, scale: float, int_fn, float_fn,
                    shape) -> ArrayLike:
    """Hardened release through the native samplers: exact integer noise
    for integer queries (counts — no float noise bits at all), the
    grid-snapped mechanism for real-valued ones. Shared by both noise
    kinds (the native twin of the reference's PyDP secure mechanisms,
    reference ``dp_computations.py:111-143``)."""
    varr = np.asarray(value)
    if varr.dtype.kind in "iu":
        result = int_fn(varr, scale).astype(np.float64)
    else:
        result = float_fn(varr.astype(np.float64), scale)
    return result if shape else float(result)


def _add_random_noise(value: ArrayLike, eps: float, delta: float,
                      l0_sensitivity: float, linf_sensitivity: float,
                      noise_kind: NoiseKind,
                      rng: Optional[np.random.Generator] = None) -> ArrayLike:
    """Adds calibrated noise; batched when ``value`` is an array
    (reference :146-176, but vectorized)."""
    shape = np.shape(value) or None
    secure = noise_ops.secure_host_noise_enabled() and rng is None
    if noise_kind == NoiseKind.LAPLACE:
        scale = noise_ops.laplace_scale(
            eps, compute_l1_sensitivity(l0_sensitivity, linf_sensitivity))
        if secure:
            # Discrete Laplace for counts, Mironov snapping otherwise.
            from pipelinedp_tpu import native
            return _secure_release(value, scale, native.discrete_laplace,
                                   native.snapping_laplace, shape)
        noise = noise_ops.np_laplace(scale, shape=shape, rng=rng)
    elif noise_kind == NoiseKind.GAUSSIAN:
        sigma = noise_ops.gaussian_sigma(
            eps, delta, compute_l2_sensitivity(l0_sensitivity,
                                               linf_sensitivity))
        if secure:
            # Exact discrete Gaussian (CKS) for counts,
            # granularity-snapped discrete Gaussian otherwise.
            from pipelinedp_tpu import native
            return _secure_release(value, sigma, native.discrete_gaussian,
                                   native.secure_gaussian, shape)
        noise = noise_ops.np_gaussian(sigma, shape=shape, rng=rng)
    else:
        raise ValueError("Noise kind must be either Laplace or Gaussian.")
    result = value + noise
    return result if shape else float(result)


def apply_laplace_mechanism(value: ArrayLike, eps: float,
                            l1_sensitivity: float) -> ArrayLike:
    """Releases ``value`` with Laplace noise of scale l1/eps
    (reference ``dp_computations.py:111-124``); batched over arrays."""
    return _add_random_noise(value, eps, 0.0, 1.0, l1_sensitivity,
                             NoiseKind.LAPLACE)


def apply_gaussian_mechanism(value: ArrayLike, eps: float, delta: float,
                             l2_sensitivity: float) -> ArrayLike:
    """Releases ``value`` with Gaussian noise at the optimal sigma for
    (eps, delta) (reference ``dp_computations.py:127-143``)."""
    return _add_random_noise(value, eps, delta, 1.0, l2_sensitivity,
                             NoiseKind.GAUSSIAN)


def equally_split_budget(eps: float, delta: float, no_mechanisms: int):
    """Splits (eps, delta) into ``no_mechanisms`` equal parts; the last part
    absorbs the floating-point residue so the shares sum exactly to the
    total (reference :224-252)."""
    if no_mechanisms <= 0:
        raise ValueError(
            "The number of mechanisms must be a positive integer.")
    eps_used = delta_used = 0
    budgets = []
    for _ in range(no_mechanisms - 1):
        budget = (eps / no_mechanisms, delta / no_mechanisms)
        eps_used += budget[0]
        delta_used += budget[1]
        budgets.append(budget)
    budgets.append((eps - eps_used, delta - delta_used))
    return budgets


def compute_dp_count(count: ArrayLike, dp_params: ScalarNoiseParams,
                     rng: Optional[np.random.Generator] = None) -> ArrayLike:
    """DP count; linf = max_contributions_per_partition (reference :255),
    or the concentration-safe (1, max_contributions) in total-cap mode."""
    l0, linf = dp_params.count_sensitivities()
    return _add_random_noise(count, dp_params.eps, dp_params.delta, l0,
                             linf, dp_params.noise_kind, rng)


def compute_dp_privacy_id_count(
        count: ArrayLike, dp_params: ScalarNoiseParams,
        rng: Optional[np.random.Generator] = None) -> ArrayLike:
    """DP privacy-id count: like compute_dp_count but with the tight
    1-per-partition sensitivities (matters only in total-cap mode)."""
    l0, linf = dp_params.pid_count_sensitivities()
    return _add_random_noise(count, dp_params.eps, dp_params.delta, l0,
                             linf, dp_params.noise_kind, rng)


def compute_dp_sum(sum_: ArrayLike, dp_params: ScalarNoiseParams,
                   rng: Optional[np.random.Generator] = None) -> ArrayLike:
    """DP sum; linf from value bounds x contributions, or per-partition sum
    bounds; zero sensitivity short-circuits to 0 (reference :278-307)."""
    l0, linf = dp_params.sum_sensitivities()
    if linf == 0:
        return np.zeros_like(sum_) if np.shape(sum_) else 0
    return _add_random_noise(sum_, dp_params.eps, dp_params.delta, l0,
                             linf, dp_params.noise_kind, rng)


def _compute_mean_for_normalized_sum(
        dp_count: ArrayLike, sum_: ArrayLike, min_value: float,
        max_value: float, eps: float, delta: float, l0_sensitivity: float,
        max_contributions_per_partition: float, noise_kind: NoiseKind,
        rng: Optional[np.random.Generator] = None) -> ArrayLike:
    """DP mean of normalized values (values shifted by the interval middle):
    noisy normalized sum divided by the DP count clamped to >= 1
    (reference :310-350)."""
    if min_value == max_value:
        return (np.full(np.shape(sum_), min_value)
                if np.shape(sum_) else min_value)
    middle = compute_middle(min_value, max_value)
    linf = max_contributions_per_partition * abs(middle - min_value)
    dp_normalized_sum = _add_random_noise(sum_, eps, delta, l0_sensitivity,
                                          linf, noise_kind, rng)
    dp_count_clamped = np.maximum(1.0, dp_count)
    result = dp_normalized_sum / dp_count_clamped
    return result if np.shape(sum_) else float(result)


def compute_dp_mean(count: ArrayLike, normalized_sum: ArrayLike,
                    dp_params: ScalarNoiseParams,
                    rng: Optional[np.random.Generator] = None):
    """DP (count, sum, mean) via the normalized-sum trick with an equal
    two-way budget split (reference :353-397)."""
    (count_eps, count_delta), (sum_eps, sum_delta) = equally_split_budget(
        dp_params.eps, dp_params.delta, 2)
    l0, linf = dp_params.count_sensitivities()
    dp_count = _add_random_noise(count, count_eps, count_delta, l0, linf,
                                 dp_params.noise_kind, rng)
    dp_mean = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum, dp_params.min_value, dp_params.max_value,
        sum_eps, sum_delta, l0, linf, dp_params.noise_kind, rng)
    if dp_params.min_value != dp_params.max_value:
        dp_mean = dp_mean + compute_middle(dp_params.min_value,
                                           dp_params.max_value)
    return dp_count, dp_mean * dp_count, dp_mean


def compute_dp_var(count: ArrayLike, normalized_sum: ArrayLike,
                   normalized_sum_squares: ArrayLike,
                   dp_params: ScalarNoiseParams,
                   rng: Optional[np.random.Generator] = None):
    """DP (count, sum, mean, variance) with an equal three-way budget split;
    variance = E[(x-mid)^2] - E[x-mid]^2 (reference :400-459)."""
    ((count_eps, count_delta), (sum_eps, sum_delta),
     (sq_eps, sq_delta)) = equally_split_budget(dp_params.eps,
                                                dp_params.delta, 3)
    l0, linf = dp_params.count_sensitivities()
    dp_count = _add_random_noise(count, count_eps, count_delta, l0, linf,
                                 dp_params.noise_kind, rng)
    dp_mean = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum, dp_params.min_value, dp_params.max_value,
        sum_eps, sum_delta, l0, linf, dp_params.noise_kind, rng)
    squares_min, squares_max = compute_squares_interval(
        dp_params.min_value, dp_params.max_value)
    dp_mean_squares = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum_squares, squares_min, squares_max, sq_eps,
        sq_delta, l0, linf, dp_params.noise_kind, rng)
    dp_var = dp_mean_squares - dp_mean**2
    if dp_params.min_value != dp_params.max_value:
        dp_mean = dp_mean + compute_middle(dp_params.min_value,
                                           dp_params.max_value)
    return dp_count, dp_mean * dp_count, dp_mean, dp_var


# ---------------------------------------------------------------------------
# Vector sum (reference :178-222)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AdditiveVectorNoiseParams:
    eps_per_coordinate: float
    delta_per_coordinate: float
    max_norm: float
    l0_sensitivity: float
    linf_sensitivity: float
    norm_kind: NormKind
    noise_kind: NoiseKind


def _clip_vector(vec: np.ndarray, max_norm: float,
                 norm_kind: NormKind) -> np.ndarray:
    """Norm-clips ``vec``; batched over leading axes (the norm is taken
    over the last axis), so one [D] vector and a [P, D] stack of
    per-partition vectors share the implementation."""
    kind = norm_kind.value
    if kind == "linf":
        return np.clip(vec, -max_norm, max_norm)
    if kind in ("l1", "l2"):
        norms = np.linalg.norm(vec, ord=int(kind[-1]), axis=-1,
                               keepdims=True)
        # Zero-norm rows pass through unscaled (factor 1), computed
        # without dividing by ~0 (overflow warnings for huge max_norm).
        factor = np.where(norms > max_norm, max_norm / np.where(
            norms > 0, norms, 1.0), 1.0)
        return vec * factor
    raise NotImplementedError(
        f"Vector norm of kind '{kind}' is not supported.")


def add_noise_vector(vec: np.ndarray,
                     noise_params: AdditiveVectorNoiseParams,
                     rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Clips by the configured norm, then adds per-coordinate noise with the
    per-coordinate budget — one batched draw over all coordinates."""
    vec = _clip_vector(np.asarray(vec, dtype=np.float64),
                       noise_params.max_norm, noise_params.norm_kind)
    return np.asarray(
        _add_random_noise(vec, noise_params.eps_per_coordinate,
                          noise_params.delta_per_coordinate,
                          noise_params.l0_sensitivity,
                          noise_params.linf_sensitivity,
                          noise_params.noise_kind, rng))


# ---------------------------------------------------------------------------
# Noise-std predictors for utility analysis (reference :462-489)
# ---------------------------------------------------------------------------


def compute_dp_count_noise_std(dp_params: ScalarNoiseParams) -> float:
    l0, linf = dp_params.count_sensitivities()
    return _noise_std(dp_params.eps, dp_params.delta, l0, linf,
                      dp_params.noise_kind)


def compute_dp_sum_noise_std(dp_params: ScalarNoiseParams) -> float:
    l0, linf = dp_params.sum_sensitivities()
    return _noise_std(dp_params.eps, dp_params.delta, l0, linf,
                      dp_params.noise_kind)
