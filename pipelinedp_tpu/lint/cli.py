"""``python -m pipelinedp_tpu.lint`` — the ``make lintcheck`` entry.

Exit 0 iff the scanned set has zero unsuppressed findings.  ``--json``
emits one store-shaped document (``{"schema_version", "name", "ts",
"payload"}`` — the same envelope ``obs/store.py`` appends), so a CI
gate can append it to a run ledger and diff per-rule finding and
suppression counts across runs.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional

from pipelinedp_tpu.lint import engine, rules

#: Store-entry name for the JSON document (ledger-diffable).
RECORD_NAME = "lint.findings"
JSON_SCHEMA_VERSION = 1


def findings_document(result: engine.LintResult,
                      ts: Optional[float] = None) -> Dict[str, Any]:
    """The ``--json`` payload in the run-ledger envelope shape."""
    return {
        "schema_version": JSON_SCHEMA_VERSION,
        "name": RECORD_NAME,
        "ts": time.time() if ts is None else ts,
        "payload": {
            "files_scanned": result.files_scanned,
            "rules_run": sorted(result.rules_run),
            "counts": result.counts(),
            "suppressed_counts": result.suppressed_counts(),
            "findings": [f.to_dict() for f in result.findings],
            "suppressed": [f.to_dict() for f in result.suppressed],
            "suppressions": [s.to_dict()
                             for s in result.suppressions],
            "unused_suppressions": [
                s.to_dict() for s in result.unused_suppressions()],
            "out_of_scope": list(result.out_of_scope),
            "ok": result.ok,
        },
    }


def _print_list() -> None:
    legacy = {v: k for k, v in rules.legacy_targets().items()}
    for rule in rules.all_rules():
        origin = (f"(ports make {legacy[rule.id]})"
                  if rule.id in legacy else "(AST-only analysis)")
        print(f"{rule.id:22s} {origin:24s} {rule.invariant}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pipelinedp_tpu.lint",
        description="AST invariant checker (the grep forest's one "
                    "successor)")
    parser.add_argument("--rule", action="append", dest="rule_ids",
                        metavar="ID",
                        help="run only this rule (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="emit one store-shaped JSON document")
    parser.add_argument("--list", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: this tree)")
    parser.add_argument("paths", nargs="*",
                        help="explicit files to scan instead of the "
                             "default set (library + bench.py)")
    args = parser.parse_args(argv)

    if args.list:
        _print_list()
        return 0

    try:
        result = engine.run(root=args.root, rule_ids=args.rule_ids,
                            paths=args.paths or None)
    except KeyError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(findings_document(result), indent=2,
                         sort_keys=True))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    n_sup = len(result.suppressed)
    if n_sup:
        counts = result.suppressed_counts()
        per_rule = ", ".join(f"{k}={v}"
                             for k, v in sorted(counts.items()))
        print(f"lint: {n_sup} suppressed finding(s) carry written "
              f"reasons ({per_rule})")
    for s in result.unused_suppressions():
        print(f"{s.path}:{s.comment_line} note: unused suppression "
              f"of '{s.rule}' — safe to delete")
    for rel in result.out_of_scope:
        print(f"{rel} warning: outside the scanned scope "
              "(pipelinedp_tpu/ + bench.py) — NOT checked")
    if result.out_of_scope and not result.files_scanned:
        print("lint: no requested file is in scope — nothing was "
              "checked")
        return 2
    if result.findings:
        print(f"lint: FAILED — {len(result.findings)} unsuppressed "
              f"finding(s) across {result.files_scanned} file(s)")
        return 1
    print(f"lint: OK — {result.files_scanned} file(s), "
          f"{len(result.rules_run)} rule(s), {n_sup} suppression(s)")
    return 0
