"""AST lint engine: one parse per file, a rule registry pass, inline
suppressions.

Every hard guarantee this repo sells — bit-identical DP replay,
exactly-once budget debits, zero orphan ``pdp-*`` threads — used to be
policed by a forest of Makefile greps plus hand-copied AST twins in the
test tree.  This engine replaces both: each invariant is ONE rule
(:mod:`pipelinedp_tpu.lint.rules`), each source file is parsed ONCE,
and every rule visits the shared tree.  Findings are structured
(``file:line rule-id message``) and deliberate exceptions are inline::

    x = time.sleep(1)  # lint: disable=nosleep(reason why this is fine)

Suppressions are first-class data, not invisibility: they are parsed,
matched to the finding they silence, counted, and reported (a CI gate
can diff suppression counts per rule exactly like finding counts).  A
``disable`` with no ``(reason)`` never suppresses — it surfaces as a
``lint-suppression`` finding instead, so every silenced invariant in
the tree carries a written justification.

The engine is stdlib-only and import-light on purpose: ``make
lintcheck`` must run in a tree whose heavyweight deps (jax) may be
broken, because lint is how you find out *why*.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Pseudo-rule id for malformed suppression comments (a ``disable``
#: with no written reason).  Not in the registry — it cannot be
#: disabled, by construction.
SUPPRESSION_RULE = "lint-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_-]+)\s*(?:\(([^)#]*)\))?")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    fix_hint: str = ""
    suppressed: bool = False
    reason: str = ""  # the suppression's written reason, when suppressed

    def format(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line} {self.rule} {self.message}{tail}"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    """One parsed ``# lint: disable=rule(reason)`` comment."""

    rule: str
    path: str
    line: int  # the code line the suppression governs
    comment_line: int  # where the comment physically sits
    reason: str
    used: bool = False

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class FileContext:
    """One parsed source file, shared by every rule.

    ``rel`` is the repo-relative forward-slash path rules scope on;
    fixtures may lint arbitrary source *as if* it lived at any ``rel``,
    which is how path-confined rules get unit-tested without touching
    the real tree.
    """

    def __init__(self, rel: str, source: str):
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.rel)
        # line -> {rule_id: Suppression}; a comment-only line's
        # suppression also governs the next non-blank code line, so the
        # repo's 72-col style can keep reasons on their own line.
        self.suppressions: Dict[int, Dict[str, Suppression]] = {}
        self.bad_suppressions: List[Finding] = []
        self._parse_suppressions()

    def _iter_comment_lines(self):
        """(line_no, comment_text, own_line) for REAL comments only —
        tokenize, not regex, so a docstring showing a suppression
        example can never register (or accidentally apply) one."""
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    line_text = self.lines[tok.start[0] - 1]
                    own = line_text.lstrip().startswith("#")
                    yield tok.start[0], tok.string, own
        except tokenize.TokenizeError:  # pragma: no cover
            return

    def _parse_suppressions(self) -> None:
        all_sups: List[Suppression] = []
        for idx, text, own_line in self._iter_comment_lines():
            for m in _SUPPRESS_RE.finditer(text):
                rule, reason = m.group(1), (m.group(2) or "").strip()
                if not reason:
                    self.bad_suppressions.append(Finding(
                        rule=SUPPRESSION_RULE, path=self.rel, line=idx,
                        message=(f"suppression of '{rule}' has no "
                                 "written reason — use "
                                 f"`# lint: disable={rule}(why)`"),
                        fix_hint="every disable must name its why"))
                    continue
                governed = idx
                if own_line:
                    # Own-line comment: governs the next code line.
                    j = idx
                    while j < len(self.lines) and (
                            not self.lines[j].strip()
                            or self.lines[j].lstrip().startswith("#")):
                        j += 1
                    governed = j + 1 if j < len(self.lines) else idx
                all_sups.append(Suppression(
                    rule=rule, path=self.rel, line=governed,
                    comment_line=idx, reason=reason))
        for sup in all_sups:
            self.suppressions.setdefault(sup.line, {})[sup.rule] = sup
        self._all_suppressions = all_sups

    def suppression_for(self, rule: str, line: int
                        ) -> Optional[Suppression]:
        return self.suppressions.get(line, {}).get(rule)

    @property
    def all_suppressions(self) -> List[Suppression]:
        return list(self._all_suppressions)


@dataclasses.dataclass
class LintResult:
    """Everything one lint pass learned about the scanned set."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: List[Finding] = dataclasses.field(default_factory=list)
    suppressions: List[Suppression] = dataclasses.field(
        default_factory=list)
    files_scanned: int = 0
    rules_run: List[str] = dataclasses.field(default_factory=list)
    #: Explicitly-requested paths NO rule scopes over (outside the
    #: library + bench.py) — an OK verdict never covers these.
    out_of_scope: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def suppressed_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.suppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def unused_suppressions(self) -> List[Suppression]:
        return [s for s in self.suppressions if not s.used]

    def extend(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)
        self.suppressions.extend(other.suppressions)
        self.files_scanned += other.files_scanned


def repo_root() -> str:
    """The tree the default scan covers: the repo this package sits in."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_python_files(root: str) -> Iterable[Tuple[str, str]]:
    """Yield ``(abs_path, rel)`` for the scanned set: the library
    package plus ``bench.py`` (per-rule scoping narrows further)."""
    targets: List[str] = []
    pkg = os.path.join(root, "pipelinedp_tpu")
    for dirpath, dirnames, files in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(files):
            if fname.endswith(".py"):
                targets.append(os.path.join(dirpath, fname))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        targets.append(bench)
    for path in sorted(targets):
        yield path, os.path.relpath(path, root).replace(os.sep, "/")


def lint_context(ctx: FileContext, rules: Sequence) -> LintResult:
    """Run ``rules`` over one already-parsed file."""
    from pipelinedp_tpu.lint import rules as rules_mod
    result = LintResult(files_scanned=1,
                        rules_run=[r.id for r in rules])
    result.findings.extend(ctx.bad_suppressions)
    run_ids = {r.id for r in rules}
    known_ids = set(rules_mod.rule_ids())
    for sup in ctx.all_suppressions:
        if sup.rule in run_ids:
            result.suppressions.append(sup)
        elif sup.rule not in known_ids:
            result.findings.append(Finding(
                rule=SUPPRESSION_RULE, path=ctx.rel,
                line=sup.comment_line,
                message=(f"suppression names unknown rule "
                         f"'{sup.rule}' — known: "
                         f"{', '.join(sorted(known_ids))}"),
                fix_hint="fix the rule id or delete the comment"))
        # else: the rule exists but is not part of this run — its
        # suppressions are neither counted nor 'unused'.
    for rule in rules:
        if not rule.applies_to(ctx.rel):
            continue
        for line, message in rule.check(ctx):
            finding = Finding(rule=rule.id, path=ctx.rel, line=line,
                              message=message, fix_hint=rule.fix_hint)
            sup = ctx.suppression_for(rule.id, line)
            if sup is not None:
                sup.used = True
                finding.suppressed = True
                finding.reason = sup.reason
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)
    return result


def lint_source(source: str, rel: str,
                rules: Optional[Sequence] = None) -> LintResult:
    """Lint a source string *as if* it lived at ``rel`` — the fixture
    seam: path-confined rules see the virtual location, so a test can
    prove `nosleep` fires on a ``time.sleep`` "in" ``streaming.py``
    without editing the real file."""
    from pipelinedp_tpu.lint import rules as rules_mod
    ctx = FileContext(rel, source)
    return lint_context(ctx, rules if rules is not None
                        else rules_mod.all_rules())


def run(root: Optional[str] = None,
        rule_ids: Optional[Sequence[str]] = None,
        paths: Optional[Sequence[str]] = None) -> LintResult:
    """Lint the tree (or an explicit ``paths`` subset) with the full
    registry or a ``rule_ids`` subset.  One ``ast.parse`` per file."""
    from pipelinedp_tpu.lint import rules as rules_mod
    root = root or repo_root()
    rules = rules_mod.select(rule_ids)
    result = LintResult(rules_run=[r.id for r in rules])
    if paths:
        file_set: List[Tuple[str, str]] = []
        for p in paths:
            ap = p if os.path.isabs(p) else os.path.join(root, p)
            file_set.append((ap, os.path.relpath(ap, root)
                             .replace(os.sep, "/")))
    else:
        file_set = list(iter_python_files(root))
    for path, rel in file_set:
        if paths and not any(r.applies_to(rel) for r in rules):
            # An explicitly-requested file every rule scopes out of:
            # "OK" must not read as "checked".
            result.out_of_scope.append(rel)
            continue
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        ctx = FileContext(rel, source)
        result.extend(lint_context(ctx, rules))
    return result
