"""One AST-based invariant checker for the whole tree.

Replaces the Makefile grep forest (nosleep, nofoldin, nostager,
noperf, noartifacts, nocost, noknobs, nopallas, noserve) and the 8
hand-copied AST twins in the test tree with ONE engine: a rule
registry over a single parse per file, structured findings, counted
inline suppressions, and three whole-program analyses grep cannot do
(rng-purity, blocking-under-lock, jit-staticness).

CLI::

    python -m pipelinedp_tpu.lint [--rule ID ...] [--json] [--list]

Test seam: :func:`check_tree` (list of formatted unsuppressed
findings, for one-line twin delegations) and
:func:`~pipelinedp_tpu.lint.engine.lint_source` (lint a source string
as if it lived at a given path, for rule fixtures).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from pipelinedp_tpu.lint import rules
from pipelinedp_tpu.lint.engine import (Finding, LintResult,
                                        Suppression, lint_source,
                                        repo_root, run)

__all__ = ["Finding", "LintResult", "Suppression", "check_tree",
           "lint_source", "repo_root", "rules", "run"]


def check_tree(*rule_ids: str, root: Optional[str] = None
               ) -> List[str]:
    """Run rules over the tree; return formatted UNSUPPRESSED findings
    (empty == invariant holds).  The one-liner the legacy test twins
    delegate to."""
    ids: Optional[Sequence[str]] = list(rule_ids) or None
    result = run(root=root, rule_ids=ids)
    return [f.format() for f in result.findings]
