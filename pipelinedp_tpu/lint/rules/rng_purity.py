"""DP-purity of randomness: every noise bit is a pure function of
(seed, content).

Checkpoint/resume replay, serve warm-reuse, and all 30+ PARITY rows
assume noise keys derive deterministically from the run seed and the
data content — an un-keyed ``np.random`` draw or a stray
``random.random()`` anywhere in the release path silently voids
bit-identical replay AND the DP guarantee (unseeded noise cannot be
audited).  This rule confines randomness to the three blessed
generator modules; every other call site is either a violation to fix or a
seeded entry seam to bless inline with a written reason — the
suppression inventory IS the repo's rng audit.

``bench.py`` is out of scope: it owns seeded synthetic *data*
generation, which is workload, not DP noise.
"""

from __future__ import annotations

import ast

from pipelinedp_tpu.lint.rules.base import (Rule, dotted_name,
                                            terminal_name)

#: Modules allowed to draw randomness: the counter-based node-noise
#: generator, the host/device noise ops, and the batched vector-noise
#: seam (the device twin of ``add_noise_vector`` — counter draws keyed
#: by (partition vocab index, coordinate)).
BLESSED_MODULES = ("pipelinedp_tpu/ops/counter_rng.py",
                   "pipelinedp_tpu/ops/noise.py",
                   "pipelinedp_tpu/ops/vector_noise.py")

#: from-imports that hide rng call sites behind bare names.
_RNG_FROM_MODULES = frozenset({"random", "numpy.random", "jax.random"})


class RngPurityRule(Rule):
    id = "rng-purity"
    legacy_target = None
    invariant = ("noise keys are pure functions of (seed, content): "
                 "randomness is drawn only in ops/counter_rng.py and "
                 "ops/noise.py; every other site is a blessed seeded "
                 "seam with a written reason, or a bug")
    fix_hint = ("derive keys via ops.counter_rng, sample via "
                "ops.noise, or bless the seeded seam with "
                "# lint: disable=rng-purity(reason)")
    blessed = BLESSED_MODULES
    scans_bench = False

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod in _RNG_FROM_MODULES:
                    yield (node.lineno,
                           f"from-import of {mod} members hides rng "
                           "call sites behind bare names")
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            dotted = dotted_name(fn) or ""
            term = terminal_name(fn)
            if (dotted.startswith("jax.random.")
                    or dotted.startswith("jrandom.")):
                yield (node.lineno, f"jax.random call: {dotted}")
            elif term == "fold_in":
                yield (node.lineno, "fold_in key derivation outside "
                       "the blessed generator modules")
            elif dotted.startswith(("np.random.", "numpy.random.")):
                yield (node.lineno, f"numpy rng call: {dotted}")
            elif (isinstance(fn, ast.Attribute)
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id == "random"):
                yield (node.lineno,
                       f"stdlib random call: random.{fn.attr}")
