"""Lock discipline: no blocking work inside a held lock body.

The exact bug class every serve review pass fixed by hand: a durable
(fsync'd) ledger write, a queue wait, or a second lock acquisition
inside ``with self._lock:`` turns one tenant's disk sync into every
other tenant's admission stall — or a lock-ordering deadlock.  The
admission path was rewritten so the fsync'd reserve runs OUTSIDE the
global lock; this rule makes that shape regression-proof.

Intra-procedural on purpose: a helper that fsyncs may legitimately be
*called* under a per-tenant lock (the budget ledger's exactly-once
discipline REQUIRES write-under-tenant-lock); what the rule polices is
the syntactic shape — blocking primitives directly inside a ``with
<lock>:`` body — which is where every real instance of the bug lived.
Deliberate holds are blessed inline with a written reason.
"""

from __future__ import annotations

import ast

from pipelinedp_tpu.lint.rules.base import (Rule, receiver_terminal,
                                            terminal_name)

#: Constructors whose result is lock-like.
_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})

#: Call terminals that block (or do durable IO) on their own.
_BLOCKING_CALLS = frozenset({"fsync", "atomic_write_json", "acquire"})

#: Constructions that open durable stores (directory scans + fsync'd
#: appends) — never inside a held lock.
_STORE_CONSTRUCTORS = frozenset({"LedgerStore", "TenantBudgetLedger",
                                 "CheckpointStore"})

#: Queue-wait attrs, flagged only on queue-shaped receivers.
_QUEUE_WAITS = frozenset({"get", "put", "join"})


def _is_queueish(name):
    if name is None:
        return False
    low = name.lower().lstrip("_")
    return low in ("q", "queue") or "queue" in low


class _LockNames(ast.NodeVisitor):
    """Collect names that hold locks: assigned from a lock factory, or
    simply named like one (``*lock*``)."""

    def __init__(self):
        self.names = set()

    def visit_Assign(self, node):
        val = node.value
        if (isinstance(val, ast.Call)
                and terminal_name(val.func) in _LOCK_FACTORIES):
            for tgt in node.targets:
                name = terminal_name(tgt)
                if name:
                    self.names.add(name)
        self.generic_visit(node)


def _lockish(expr, lock_names):
    """Is this with-item expression a lock (or a lock-returning
    call, e.g. ``self._tenant_lock(t)``)?"""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = terminal_name(expr)
    if name is None:
        return False
    return name in lock_names or "lock" in name.lower()


class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    legacy_target = None
    invariant = ("a held lock body never fsyncs, waits on a queue, "
                 "acquires another lock, or constructs a durable "
                 "store — one tenant's disk sync must not serialize "
                 "every other tenant's admission, and nested "
                 "acquisitions are deadlock bait")
    fix_hint = ("move the blocking work outside the with-block "
                "(reserve/commit OUTSIDE the admission lock, like "
                "serve.service does), or bless the hold with "
                "# lint: disable=blocking-under-lock(reason)")

    def check(self, ctx):
        collector = _LockNames()
        collector.visit(ctx.tree)
        lock_names = collector.names
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_lockish(item.context_expr, lock_names)
                       for item in node.items):
                continue
            yield from self._scan_body(node, lock_names)

    def _scan_body(self, with_node, lock_names):
        def is_lock_region(n):
            return (isinstance(n, (ast.With, ast.AsyncWith))
                    and any(_lockish(i.context_expr, lock_names)
                            for i in n.items))

        def walk(node):
            for child in ast.iter_child_nodes(node):
                # Deferred bodies run later, outside the hold.
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                yield child
                # A nested lock region is flagged here but scanned as
                # its own region by check() — don't double-visit it.
                if is_lock_region(child):
                    continue
                yield from walk(child)

        for stmt in with_node.body:
            if is_lock_region(stmt):
                # Flag the acquisition ONCE; the inner body is scanned
                # by check()'s own iteration over With nodes.
                yield (stmt.lineno, "nested lock acquisition while "
                       "holding a lock")
                continue
            nodes = [stmt] + list(walk(stmt))
            for node in nodes:
                if is_lock_region(node):
                    yield (node.lineno,
                           "nested lock acquisition while "
                           "holding a lock")
                if not isinstance(node, ast.Call):
                    continue
                term = terminal_name(node.func)
                if term in _BLOCKING_CALLS:
                    yield (node.lineno,
                           f"{term}() inside a held lock body")
                elif term in _STORE_CONSTRUCTORS:
                    yield (node.lineno,
                           f"{term} construction inside a held lock "
                           "body")
                elif (term in _QUEUE_WAITS
                      and _is_queueish(
                          receiver_terminal(node.func))):
                    yield (node.lineno,
                           f"queue .{term}() wait inside a held lock "
                           "body")
