"""The nine Makefile grep lints, ported to precise AST rules.

Each rule keeps the legacy target's name as its id (so `make nosleep`
stays meaningful as a thin alias) and the legacy scoping, but gains
what grep never had: strings and docstrings can mention the banned
names freely, aliases (``_time.sleep``) are still caught, and the
"max 2 stager sites, only in these functions" shape checks that used
to live only in the test twins are enforced everywhere the engine
runs.
"""

from __future__ import annotations

import ast

from pipelinedp_tpu.lint.rules.base import (Rule, dotted_name,
                                            import_bindings,
                                            receiver_terminal,
                                            subtree_names,
                                            terminal_name,
                                            walk_with_function)


class NoSleepRule(Rule):
    """No direct ``time.sleep`` and no bare ``threading.Thread``."""

    id = "nosleep"
    legacy_target = "nosleep"
    invariant = ("waits route through the injectable resilience clock; "
                 "worker threads through the ingest executor's "
                 "cancellable lifecycle (fault kills must drain to "
                 "zero orphan pdp-* threads)")
    fix_hint = ("use pipelinedp_tpu.resilience.clock for sleeps and "
                "the pipelinedp_tpu.ingest executor for threads")
    blessed = ()
    _SLEEP_EXEMPT = ("pipelinedp_tpu/resilience/clock.py",)
    _THREAD_EXEMPT = ("pipelinedp_tpu/ingest/",
                      "pipelinedp_tpu/resilience/")

    def check(self, ctx):
        sleep_ok = any(ctx.rel == p or ctx.rel.startswith(p)
                       for p in self._SLEEP_EXEMPT)
        thread_ok = any(ctx.rel == p or ctx.rel.startswith(p)
                        for p in self._THREAD_EXEMPT)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                recv = receiver_terminal(fn)
                if (not sleep_ok and terminal_name(fn) == "sleep"
                        and recv is not None
                        and recv.endswith("time")):
                    yield (node.lineno,
                           "direct time.sleep — waits must route "
                           "through resilience.clock")
                if (not thread_ok
                        and terminal_name(fn) == "Thread"
                        and recv == "threading"):
                    yield (node.lineno,
                           "bare threading.Thread — worker threads "
                           "must use the ingest executor")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if not sleep_ok and mod == "time" and "sleep" in names:
                    yield (node.lineno,
                           "from-import of time.sleep — waits must "
                           "route through resilience.clock")
                if (not thread_ok and mod == "threading"
                        and "Thread" in names):
                    yield (node.lineno,
                           "from-import of threading.Thread — worker "
                           "threads must use the ingest executor")


class NoFoldinRule(Rule):
    """No per-element ``vmap(fold_in)`` key schedules."""

    id = "nofoldin"
    legacy_target = "nofoldin"
    invariant = ("per-element vmap(fold_in) rebuilds a full threefry "
                 "key schedule per element — the cost the counter-based "
                 "node-noise generator removed from the quantile walk")
    fix_hint = "use pipelinedp_tpu.ops.counter_rng (counter-based keys)"
    blessed = ("pipelinedp_tpu/ops/counter_rng.py",)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fnames = subtree_names(node.func)
            if "vmap" not in fnames and "fold_in" not in fnames:
                continue
            allnames = subtree_names(node)
            if "vmap" in allnames and "fold_in" in allnames:
                yield (node.lineno,
                       "vmap(fold_in) per-element key construction")


class NoStagerRule(Rule):
    """``BackgroundStager`` construction is confined, and the two
    consumer modules keep exactly their blessed sites."""

    id = "nostager"
    legacy_target = "nostager"
    invariant = ("pass-B restreaming flows through the sweep planner's "
                 "ONE stream source (and the sketch phase through its "
                 "one accumulation loop); stray stager constructions "
                 "silently reintroduce per-tile restreaming")
    fix_hint = ("stream through streaming.run_sweep / "
                "sketch.engine._accumulate_stream / the ingest "
                "package; do not construct BackgroundStager directly")
    blessed = ("pipelinedp_tpu/ingest/",)
    #: consumer module -> (allowed enclosing functions, max sites).
    _CONSUMERS = {
        "pipelinedp_tpu/streaming.py": (
            frozenset({"_stream_impl", "run_sweep"}), 2,
            "pass A's overlapped loop (inside the elastic wrapper's "
            "_stream_impl) and run_sweep"),
        "pipelinedp_tpu/sketch/engine.py": (
            frozenset({"_accumulate_stream"}), 1,
            "the sketch accumulation loop"),
    }

    def check(self, ctx):
        sites = []
        for node, func in walk_with_function(ctx.tree):
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "BackgroundStager"):
                sites.append((node.lineno, func))
        consumer = self._CONSUMERS.get(ctx.rel)
        if consumer is None:
            for line, _ in sites:
                yield (line, "direct BackgroundStager construction "
                       "outside ingest/ and the blessed consumers")
            return
        allowed, max_sites, prose = consumer
        for line, func in sites:
            if func not in allowed:
                yield (line,
                       f"BackgroundStager site in '{func}' — only "
                       f"{prose} may build stagers here")
        if len(sites) > max_sites:
            for line, _ in sites[max_sites:]:
                yield (line,
                       f"{len(sites)} stager sites in {ctx.rel} "
                       f"(max {max_sites}: {prose})")


class NoPerfRule(Rule):
    """No raw ``perf_counter`` outside obs/, and ``obs/monitor.py``
    never touches the ``time`` module at all."""

    id = "noperf"
    legacy_target = "noperf"
    invariant = ("measured phases flow through obs spans so they land "
                 "in the run ledger; the watchdog's deadline story "
                 "rides the injectable clock, so monitor.py gets the "
                 "stricter no-time-module check")
    fix_hint = ("time through pipelinedp_tpu.obs spans; in "
                "obs/monitor.py use the injectable resilience clock")
    _MONITOR = "pipelinedp_tpu/obs/monitor.py"

    def check(self, ctx):
        in_obs = ctx.rel.startswith("pipelinedp_tpu/obs/")
        is_monitor = ctx.rel == self._MONITOR
        if in_obs and not is_monitor:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if terminal_name(fn) == "perf_counter":
                    yield (node.lineno,
                           "raw perf_counter timing — route through "
                           "obs spans" if not is_monitor else
                           "raw perf_counter in the monitor — use the "
                           "injectable clock")
            if not is_monitor:
                continue
            # monitor.py: ANY use of the time module is a finding
            # (time.monotonic would dodge a perf_counter-only check).
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = getattr(node, "module", "") or ""
                names = [a.name for a in node.names]
                if mod == "time" or "time" in names:
                    yield (node.lineno,
                           "obs/monitor.py imports time — all timing "
                           "must ride the injectable clock")
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id in ("time", "_time")):
                yield (node.lineno,
                       f"obs/monitor.py touches time.{node.attr} — "
                       "all timing must ride the injectable clock")


class NoArtifactsRule(Rule):
    """No ad-hoc ``json.dump`` file writes outside obs/ and plan/."""

    id = "noartifacts"
    legacy_target = "noartifacts"
    invariant = ("run knowledge lands in the schema-versioned "
                 "report/store/plan, never scattered one-off JSON "
                 "files (bench.py is the one blessed artifact emitter)")
    fix_hint = ("route through pipelinedp_tpu.obs (report/store) or "
                "pipelinedp_tpu.plan (the atomic plan file)")
    blessed = ("pipelinedp_tpu/obs/", "pipelinedp_tpu/plan/")
    scans_bench = False  # bench.py is the blessed emitter

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "dump"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "json"):
                yield (node.lineno, "ad-hoc json.dump artifact write")


class NoCostRule(Rule):
    """Compiled-program analysis calls confined to obs/."""

    id = "nocost"
    legacy_target = "nocost"
    invariant = ("cost_analysis/memory_analysis/live_arrays flow "
                 "through the device-cost observatory so every "
                 "measurement lands in the versioned run report")
    fix_hint = ("use pipelinedp_tpu.obs.costs (instrumented_jit / "
                "sample_live_bytes)")
    blessed = ("pipelinedp_tpu/obs/",)
    _BANNED = frozenset({"cost_analysis", "memory_analysis",
                         "live_arrays"})

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and terminal_name(node.func) in self._BANNED):
                yield (node.lineno,
                       f"direct {terminal_name(node.func)}() call")


class NoKnobsRule(Rule):
    """Registered knob constants are read only through the plan
    registry; the defining modules keep Store-context seams."""

    id = "noknobs"
    legacy_target = "noknobs"
    invariant = ("every knob consumer resolves through plan.knobs "
                 "(env > seam > plan file > default) so an autotuned "
                 "plan can steer the value and the resolution lands in "
                 "the run report's plan section")
    fix_hint = ("resolve through pipelinedp_tpu.plan (knobs.value / "
                "resolve / seam_override)")
    blessed = ("pipelinedp_tpu/plan/",)
    KNOB_CONSTANTS = frozenset({"_SUBHIST_BYTE_CAP",
                                "_SELECT_UNITS_CAP",
                                "_TREE_ROWS_CAP", "_Q_CHUNK"})
    DEFINING = {"_SUBHIST_BYTE_CAP": "pipelinedp_tpu/jax_engine.py",
                "_SELECT_UNITS_CAP": "pipelinedp_tpu/streaming.py",
                "_TREE_ROWS_CAP": "pipelinedp_tpu/streaming.py",
                "_Q_CHUNK": "pipelinedp_tpu/streaming.py"}

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            name = ctxk = None
            if (isinstance(node, ast.Name)
                    and node.id in self.KNOB_CONSTANTS):
                name, ctxk = node.id, node.ctx
            elif (isinstance(node, ast.Attribute)
                  and node.attr in self.KNOB_CONSTANTS):
                name, ctxk = node.attr, node.ctx
            if name is None:
                continue
            if (isinstance(ctxk, ast.Store)
                    and ctx.rel == self.DEFINING.get(name)):
                continue  # the definition IS the seam
            yield (node.lineno, f"direct knob-constant access: {name}")


class NoPallasRule(Rule):
    """Pallas imports confined to ops/kernels/."""

    id = "nopallas"
    legacy_target = "nopallas"
    invariant = ("every module dispatches through ops.kernels "
                 "(kernel_backend knob -> select_backend) so fallback "
                 "events, envelope checks and the interpret-mode story "
                 "stay in ONE place; you cannot call pallas without "
                 "importing it, so the import ban is the precise form")
    fix_hint = "dispatch through pipelinedp_tpu.ops.kernels"
    blessed = ("pipelinedp_tpu/ops/kernels/",)

    def check(self, ctx):
        # One finding per line: a nested chain like
        # jax.experimental.pallas.pallas_call(...) matches several
        # node forms but is one violation.
        hits = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                if any("pallas" in n
                       for n in import_bindings(node)):
                    hits.setdefault(node.lineno,
                                    "pallas import outside "
                                    "ops/kernels/")
            elif isinstance(node, ast.Call):
                # The import ban alone misses attribute access through
                # an already-imported submodule
                # (jax.experimental.pallas.pallas_call(...)) and the
                # conventional `pl.` alias — the legacy grep banned
                # both call forms explicitly.
                if terminal_name(node.func) == "pallas_call":
                    hits.setdefault(node.lineno,
                                    "pallas_call site outside "
                                    "ops/kernels/")
            elif isinstance(node, ast.Attribute):
                dotted = dotted_name(node) or ""
                if (dotted.startswith("pl.")
                        or ".pallas." in f".{dotted}."):
                    hits.setdefault(node.lineno,
                                    f"pallas attribute access "
                                    f"({dotted}) outside ops/kernels/")
        for line in sorted(hits):
            yield (line, hits[line])


class NoServeRule(Rule):
    """The service depends on the engine, never the reverse; durable
    budget-ledger state has ONE writer stack."""

    id = "noserve"
    legacy_target = "noserve"
    invariant = ("batch mode stays byte-for-byte oblivious to serving "
                 "(no serve imports outside serve/), and "
                 "TenantBudgetLedger construction is confined to "
                 "serve/ + budget_accounting.py so budget debits have "
                 "one durable writer stack")
    fix_hint = ("route budget debits through the serve layer's "
                "durable ledger; never import pipelinedp_tpu.serve "
                "from engine modules")
    blessed = ("pipelinedp_tpu/serve/",)
    _LEDGER_EXTRA_BLESSED = ("pipelinedp_tpu/budget_accounting.py",)

    def check(self, ctx):
        ledger_ok = ctx.rel in self._LEDGER_EXTRA_BLESSED
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.Import, ast.ImportFrom))
                    and ctx.rel != "bench.py"):
                if any(n == "pipelinedp_tpu.serve"
                       or n.startswith("pipelinedp_tpu.serve.")
                       for n in import_bindings(node)):
                    yield (node.lineno,
                           "serve import in a batch-engine module — "
                           "the service depends on the engine, never "
                           "the reverse")
            if (not ledger_ok and isinstance(node, ast.Call)
                    and terminal_name(node.func)
                    == "TenantBudgetLedger"):
                yield (node.lineno,
                       "TenantBudgetLedger construction outside "
                       "serve/ + budget_accounting.py")


class FusionMaskingRule(Rule):
    """Fused-batch pad-mask construction is confined to the serve
    fusion layer + the blessed ``jax_engine`` batched-kernel seam."""

    id = "fusion-masking"
    legacy_target = None  # born with `make fusecheck`, never a grep
    invariant = ("request padding for fused batches is built ONLY by "
                 "serve/fusion.pad_request_to_bucket (the validity "
                 "mask is constructed alongside the padding) and "
                 "dispatched ONLY through jax_engine's batched-kernel "
                 "seam from serve/fusion.py — the engine must never "
                 "see unmasked padded rows, because only the mask "
                 "keeps bucket padding out of released values")
    fix_hint = ("pad through pipelinedp_tpu.serve.fusion."
                "pad_request_to_bucket and dispatch fused batches "
                "from serve/fusion.py only")
    blessed = ("pipelinedp_tpu/serve/fusion.py",)
    #: jax_engine DEFINES the batched kernel (and may dispatch it from
    #: its own blessed seam); everywhere else a dispatch site means a
    #: second pad/mask policy is growing.
    _KERNEL_EXTRA_BLESSED = ("pipelinedp_tpu/jax_engine.py",)

    def check(self, ctx):
        kernel_ok = ctx.rel in self._KERNEL_EXTRA_BLESSED
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name == "pad_request_to_bucket":
                yield (node.lineno,
                       "fused-batch pad-mask construction outside "
                       "serve/fusion.py")
            elif (name == "fused_aggregate_batch_kernel"
                  and not kernel_ok):
                yield (node.lineno,
                       "batched-kernel dispatch outside the blessed "
                       "serve-fusion seam")


class SketchConfinementRule(Rule):
    """Key hashing and candidate-table construction are confined to
    ``sketch/``; raw builtin ``hash()`` is banned on keys everywhere.

    Python's builtin hash is salted per process (``PYTHONHASHSEED``):
    a key bucketed with it lands in DIFFERENT buckets across runs,
    resumes and mesh processes, which silently voids sketch replay
    and candidate-table stability. The seeded stable hash
    (``sketch/hashing.py``) is the one blessed key hash for
    replayable key→bucket maps; ``__hash__`` protocol implementations
    are exempt (dict/set membership is in-process by definition), and
    equality-semantic in-process uses stay on builtin hash() via a
    reasoned suppression."""

    id = "sketch-confinement"
    legacy_target = None  # born with `make sketchcheck`, never a grep
    invariant = ("key→bucket maps are pure functions of (key bytes, "
                 "seed): raw hash() is process-salted and cannot "
                 "replay; bucket derivation and candidate tables have "
                 "ONE owner (sketch/) so the DP selection's "
                 "sensitivity story cannot fork")
    fix_hint = ("hash keys via pipelinedp_tpu.sketch.hashing "
                "(stable_hash64 / stable_hash_any); build candidate/"
                "bucket tables only inside sketch/")
    blessed = ("pipelinedp_tpu/sketch/",)
    #: Construction confined to sketch/: deriving bucket rows and
    #: building the key→candidate-id table ARE the sketch mechanism —
    #: a second site means a second sensitivity story.
    _CONFINED_CALLS = frozenset({"bucket_ids", "build_candidate_table"})

    def check(self, ctx):
        for node, func in walk_with_function(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == "hash"
                    and func != "__hash__"):
                yield (node.lineno,
                       "raw builtin hash() — process-salted, cannot "
                       "replay; use sketch.hashing.stable_hash_any")
            elif terminal_name(fn) in self._CONFINED_CALLS:
                yield (node.lineno,
                       f"{terminal_name(fn)}() outside sketch/ — "
                       "bucket/candidate-table construction has one "
                       "owner")


class SocketConfinementRule(Rule):
    """Raw wire machinery (``socket`` / ``http.server`` /
    ``socketserver``) is confined to ``obs/http.py``.

    The introspection endpoint is the repo's ONE wire surface, and it
    is read-only by construction. A second module opening sockets
    would grow a second listener lifecycle outside the serve drain
    discipline (orphan accept threads survive ``Service.close``) and a
    second place where per-tenant budget state could leak off-box.
    You cannot serve a port without importing the machinery, so the
    import ban is the precise form — client-side stdlib
    (``urllib``, ``http.client``) stays free for tests and tools."""

    id = "socket-confinement"
    legacy_target = None  # born with `make metricscheck`, never a grep
    invariant = ("the process has ONE wire surface — the read-only "
                 "obs/http.py introspection endpoint, whose accept "
                 "thread the serve lifecycle starts and drains; any "
                 "other socket/http.server/socketserver import grows "
                 "an unmanaged listener")
    fix_hint = ("expose data through pipelinedp_tpu.obs.http "
                "(maybe_start / IntrospectionServer); never open "
                "sockets elsewhere")
    blessed = ("pipelinedp_tpu/obs/http.py",)
    _BANNED_MODULES = ("socket", "socketserver", "http.server")

    def check(self, ctx):
        hits = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            for name in import_bindings(node):
                if any(name == m or name.startswith(m + ".")
                       for m in self._BANNED_MODULES):
                    hits.setdefault(
                        node.lineno,
                        f"wire-machinery import ({name}) outside "
                        "obs/http.py — the introspection endpoint is "
                        "the one wire surface")
        for line in sorted(hits):
            yield (line, hits[line])


class CollectiveConfinementRule(Rule):
    """Cross-device collectives (``psum`` / ``psum_scatter`` /
    ``all_gather``) are confined to ``parallel/sharded.py``.

    The topology layer holds three invariants at its one seam: the
    exchange ORDER is fixed (hier-vs-flat bit-parity rests on both
    paths reducing through the same deterministic trees — PARITY row
    43), every exchange is byte-accounted (``comms.ici_bytes`` /
    ``comms.dcn_bytes``), and the ``mesh_topology`` knob steers every
    exchange. A raw ``jax.lax`` collective anywhere else is invisible
    to all three: it ignores the topology (owner-block traffic back on
    DCN at ICI cadence), skips the byte meter, and its reduction
    grouping is outside the parity contract."""

    id = "collective-confinement"
    legacy_target = None  # born with `make topocheck`, never a grep
    invariant = ("every cross-device collective goes through "
                 "parallel/sharded.py's topology-aware helpers "
                 "(combine_shards / gather_blocks / scatter_to_owner): "
                 "ONE exchange seam carries the hier-vs-flat parity "
                 "contract, the mesh_topology knob and the ici/dcn "
                 "byte accounting")
    fix_hint = ("call parallel.sharded.combine_shards / gather_blocks "
                "/ scatter_to_owner (pass topology_of(mesh)) instead "
                "of raw jax.lax psum/psum_scatter/all_gather")
    blessed = ("pipelinedp_tpu/parallel/sharded.py",)
    _COLLECTIVES = frozenset({"psum", "psum_scatter", "all_gather"})

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name in self._COLLECTIVES:
                yield (node.lineno,
                       f"raw collective {name}() outside "
                       "parallel/sharded.py — exchanges go through "
                       "the topology-aware seam")


PORTED_RULES = (NoSleepRule, NoFoldinRule, NoStagerRule, NoPerfRule,
                NoArtifactsRule, NoCostRule, NoKnobsRule,
                NoPallasRule, NoServeRule)
