"""Jit-staticness: nothing ambient is read at trace time.

A function dispatched through ``instrumented_jit``/``jax.jit`` runs
its Python body ONCE per program signature; everything it reads from
the environment — ``os.environ``, wall-clock ``time.*``, a knob
constant — freezes into the compiled program and silently stops
responding to the planner, the env, or the clock (the shape-blind
knob-read bug PR 9 fixed is this rule's seed fixture).  Values that
must vary pass as (possibly static) arguments; values that must not
vary don't belong in a traced body at all.
"""

from __future__ import annotations

import ast

from pipelinedp_tpu.lint.rules.base import (Rule, subtree_names,
                                            terminal_name)
from pipelinedp_tpu.lint.rules.confinement import NoKnobsRule

_JIT_NAMES = frozenset({"jit", "instrumented_jit"})


def _decorator_is_jit(dec) -> bool:
    if isinstance(dec, ast.Call):
        if terminal_name(dec.func) in _JIT_NAMES:
            return True
        # functools.partial(jax.jit, ...) style
        if terminal_name(dec.func) == "partial":
            return bool(_JIT_NAMES & subtree_names(dec))
        return False
    return terminal_name(dec) in _JIT_NAMES


def _jitted_function_names(tree) -> set:
    """Functions decorated with a jit wrapper, plus functions passed
    by name into ``jax.jit(fn, ...)`` / ``instrumented_jit(fn, ...)``
    assignments (the ``program = instrumented_jit(_kernel, ...)``
    idiom)."""
    jitted = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_is_jit(d) for d in node.decorator_list):
                jitted.add(node.name)
        elif (isinstance(node, ast.Call)
              and terminal_name(node.func) in _JIT_NAMES
              and node.args
              and isinstance(node.args[0], ast.Name)):
            jitted.add(node.args[0].id)
    return jitted


class JitStaticnessRule(Rule):
    id = "jit-staticness"
    legacy_target = None
    invariant = ("traced bodies read nothing ambient: os.environ, "
                 "time.*, and registered knob constants freeze at "
                 "trace time and stop responding to the planner/env — "
                 "pass them in as (static) arguments instead")
    fix_hint = ("hoist the read to the call site and pass it as an "
                "argument (static_argnames if it shapes the program)")

    def check(self, ctx):
        jitted = _jitted_function_names(ctx.tree)
        if not jitted:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name not in jitted:
                continue
            yield from self._scan_traced_body(node)

    def _scan_traced_body(self, fn):
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, ast.Attribute):
                if (node.attr == "environ"
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "os"):
                    yield (node.lineno,
                           f"os.environ read inside traced "
                           f"'{fn.name}' freezes at trace time")
            if isinstance(node, ast.Call):
                term = terminal_name(node.func)
                recv = (node.func.value
                        if isinstance(node.func, ast.Attribute)
                        else None)
                if term == "getenv" and isinstance(recv, ast.Name) \
                        and recv.id == "os":
                    yield (node.lineno,
                           f"os.getenv inside traced '{fn.name}' "
                           "freezes at trace time")
                elif (isinstance(recv, ast.Name)
                      and recv.id in ("time", "_time")):
                    yield (node.lineno,
                           f"time.{term} inside traced '{fn.name}' "
                           "freezes at trace time")
                elif (term == "value"
                      and isinstance(recv, ast.Name)
                      and recv.id in ("knobs", "_knobs")):
                    # The megasweep contract (ISSUE 18): config values
                    # — batch widths, bounds, eps-splits — reach the
                    # batched kernels as RUNTIME inputs; a knob read
                    # inside the traced body bakes one plan's value
                    # into the compiled program and every new config
                    # batch recompiles.
                    yield (node.lineno,
                           f"knobs.value read inside traced "
                           f"'{fn.name}' freezes the planner's value "
                           "at trace time")
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if (name in NoKnobsRule.KNOB_CONSTANTS
                    and isinstance(getattr(node, "ctx", None),
                                   ast.Load)):
                yield (node.lineno,
                       f"knob constant {name} read inside traced "
                       f"'{fn.name}' freezes the planner's value")
