"""The rule registry: 9 ported Makefile lints + 7 born-AST analyses.

Adding a rule: subclass :class:`~pipelinedp_tpu.lint.rules.base.Rule`
in a module here, list it in :data:`ALL_RULE_CLASSES`, and add a
bad+clean fixture pair to ``tests/test_lint.py`` (the registry
meta-test will fail until the fixture exists — see
``contributing/CONTRIBUTING.md``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from pipelinedp_tpu.lint.rules.base import Rule
from pipelinedp_tpu.lint.rules.confinement import (
    CollectiveConfinementRule, FusionMaskingRule, PORTED_RULES,
    SketchConfinementRule, SocketConfinementRule)
from pipelinedp_tpu.lint.rules.jit_static import JitStaticnessRule
from pipelinedp_tpu.lint.rules.locks import BlockingUnderLockRule
from pipelinedp_tpu.lint.rules.rng_purity import RngPurityRule

ALL_RULE_CLASSES = tuple(PORTED_RULES) + (
    RngPurityRule, BlockingUnderLockRule, JitStaticnessRule,
    FusionMaskingRule, SketchConfinementRule, SocketConfinementRule,
    CollectiveConfinementRule)

_REGISTRY: Dict[str, Rule] = {}
for _cls in ALL_RULE_CLASSES:
    _rule = _cls()
    assert _rule.id and _rule.id not in _REGISTRY, _cls
    _REGISTRY[_rule.id] = _rule


def all_rules() -> List[Rule]:
    return list(_REGISTRY.values())


def rule_ids() -> List[str]:
    return list(_REGISTRY)


def get(rule_id: str) -> Rule:
    try:
        return _REGISTRY[rule_id]
    except KeyError:
        raise KeyError(
            f"unknown lint rule '{rule_id}' — known: "
            f"{', '.join(_REGISTRY)}") from None


def select(rule_ids_seq: Optional[Sequence[str]]) -> List[Rule]:
    if rule_ids_seq is None:
        return all_rules()
    return [get(rid) for rid in rule_ids_seq]


def legacy_targets() -> Dict[str, str]:
    """Makefile grep target -> owning rule id (the port inventory)."""
    return {r.legacy_target: r.id for r in _REGISTRY.values()
            if r.legacy_target is not None}
