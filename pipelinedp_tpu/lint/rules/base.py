"""Rule base class + the small AST vocabulary every rule shares."""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

#: (line, message) pairs — the engine wraps them into Findings.
RuleHits = Iterable[Tuple[int, str]]


class Rule:
    """One invariant: an id, the prose of what it protects, a fix
    hint, and a ``check`` over a parsed file.

    ``legacy_target`` names the Makefile grep this rule superseded
    (None for the born-AST analyses); the registry meta-test asserts
    every legacy target still has an owner.
    """

    id: str = ""
    legacy_target: Optional[str] = None
    invariant: str = ""
    fix_hint: str = ""
    #: Path prefixes (or exact files) this rule never scans — the
    #: blessed modules.  Documentation AND behavior: ``applies_to``
    #: consults it, and the README rule table renders it.
    blessed: Sequence[str] = ()
    #: Scan scope; None means the engine default (library + bench.py).
    #: A rule may narrow to library-only by overriding ``scans_bench``.
    scans_bench: bool = True

    def applies_to(self, rel: str) -> bool:
        if rel == "bench.py":
            return self.scans_bench
        if not rel.startswith("pipelinedp_tpu/"):
            return False
        return not any(
            rel == b or rel.startswith(b) for b in self.blessed)

    def check(self, ctx) -> RuleHits:
        raise NotImplementedError


def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain
    (``a.b.c`` -> ``c``; ``f`` -> ``f``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def receiver_terminal(node: ast.AST) -> Optional[str]:
    """For ``x.y.attr`` the terminal name of the receiver ``x.y``."""
    if isinstance(node, ast.Attribute):
        return terminal_name(node.value)
    return None


def subtree_names(node: ast.AST) -> set:
    """Every identifier mentioned anywhere under ``node`` (Name ids
    and Attribute attrs) — the 'does this expression touch X at all'
    primitive."""
    out = set()
    for child in ast.walk(node):
        if isinstance(child, ast.Name):
            out.add(child.id)
        elif isinstance(child, ast.Attribute):
            out.add(child.attr)
    return out


def import_bindings(node: ast.AST) -> List[str]:
    """The dotted module/member names an import statement binds."""
    names: List[str] = []
    if isinstance(node, ast.ImportFrom) and node.module:
        names.append(node.module)
        names.extend(f"{node.module}.{a.name}" for a in node.names)
    elif isinstance(node, ast.Import):
        names.extend(a.name for a in node.names)
    return names


def walk_with_function(tree: ast.AST
                       ) -> Iterator[Tuple[ast.AST, str]]:
    """Yield ``(node, enclosing_function_name)`` pairs;
    ``<module>`` at top level."""

    def visit(node: ast.AST, func: str):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        yield node, func
        for child in ast.iter_child_nodes(node):
            yield from visit(child, func)

    yield from visit(tree, "<module>")
