"""Shared scalar validators for privacy parameters.

Capability parity with the reference's ``pipeline_dp/input_validators.py:17-34``
(epsilon strictly positive, delta in [0, 1)), written fresh for the TPU build.
"""

from __future__ import annotations


def validate_epsilon_delta(epsilon: float, delta: float, who: str) -> None:
    """Raises ValueError unless ``epsilon > 0`` and ``0 <= delta < 1``.

    Args:
      epsilon: the epsilon privacy parameter.
      delta: the delta privacy parameter.
      who: name of the calling object, used in error messages.
    """
    if epsilon is None:
        raise ValueError(f"{who}: epsilon must be set")
    if delta is None:
        raise ValueError(f"{who}: delta must be set")
    if epsilon <= 0:
        raise ValueError(
            f"{who}: epsilon must be positive, not {epsilon}.")
    if delta < 0 or delta >= 1:
        raise ValueError(
            f"{who}: delta must be in [0, 1), not {delta}.")
