"""Native host library: hardened noise for DP releases.

The on-device (TPU) path draws noise with ``jax.random`` — statistically
correct, and safe for the aggregate pipelines this framework targets, but
a textbook floating-point Laplace leaks information through the noise
sample's low-order bits (Mironov, CCS 2012). The reference delegates its
host noise to the C++ google/differential-privacy library, which hardens
against this; this package is the TPU framework's native twin:

* ``snapping_laplace(values, scale, bound)`` — Mironov's snapping
  mechanism over a ChaCha20 CSPRNG,
* ``discrete_laplace(counts, scale)`` — exact two-sided geometric noise
  for integer releases (no float noise bits at all),
* ``seed(n)`` / ``seed_from_os()`` — deterministic seeding for tests,
  OS entropy otherwise.

The C++ source (``secure_noise.cc``) is compiled on first use with the
toolchain's ``g++`` into a cached shared library next to this file (or
``$PIPELINEDP_TPU_NATIVE_CACHE``). Environments without a compiler get
``NativeUnavailableError`` and callers fall back to the NumPy path —
``ops/noise.py`` documents the resulting security posture.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "secure_noise.cc")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_ERROR: Optional[str] = None


class NativeUnavailableError(RuntimeError):
    """The native library could not be built/loaded on this host."""


def _cache_dir() -> str:
    override = os.environ.get("PIPELINEDP_TPU_NATIVE_CACHE")
    if override:
        os.makedirs(override, exist_ok=True)
        return override
    d = os.path.dirname(__file__)
    return d if os.access(d, os.W_OK) else tempfile.gettempdir()


def _build() -> str:
    out = os.path.join(_cache_dir(), "_secure_noise.so")
    if (os.path.exists(out) and
            os.path.getmtime(out) >= os.path.getmtime(_SRC)):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeUnavailableError(
            f"g++ failed building secure_noise: {proc.stderr[-500:]}")
    os.replace(tmp, out)  # atomic: concurrent builders race harmlessly
    return out


def _lib() -> ctypes.CDLL:
    global _LIB, _LOAD_ERROR
    if _LIB is not None:
        return _LIB
    if _LOAD_ERROR is not None:
        raise NativeUnavailableError(_LOAD_ERROR)
    with _LOCK:
        if _LIB is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build())
        except (OSError, NativeUnavailableError) as e:
            _LOAD_ERROR = str(e)
            raise NativeUnavailableError(_LOAD_ERROR) from e
        lib.sn_seed.argtypes = [ctypes.c_uint64]
        lib.sn_seed_from_os.argtypes = []
        lib.sn_snapping_laplace.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_double, ctypes.c_double]
        lib.sn_snapping_laplace.restype = ctypes.c_double
        lib.sn_uniform.argtypes = [ctypes.POINTER(ctypes.c_double),
                                   ctypes.c_int64]
        lib.sn_discrete_laplace.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_double]
        _LIB = lib
        return _LIB


def available() -> bool:
    """True when the native library can be (or was) built and loaded.
    May spawn a g++ build on first call — use :func:`is_loaded` for a
    side-effect-free check."""
    try:
        _lib()
        return True
    except NativeUnavailableError:
        return False


def is_loaded() -> bool:
    """True iff the library is already loaded in this process. Never
    triggers a build."""
    return _LIB is not None


def seed(n: int) -> None:
    """Deterministic CSPRNG seeding — tests only."""
    _lib().sn_seed(ctypes.c_uint64(n & (2**64 - 1)))


def seed_from_os() -> None:
    """Re-key from OS entropy (e.g. after fork)."""
    _lib().sn_seed_from_os()


def snapping_laplace(values, scale: float,
                     bound: Optional[float] = None) -> np.ndarray:
    """Snapping-Laplace release of ``values`` with noise scale ``scale``.

    Returns values + Laplace(scale) noise, rounded to the snapping
    resolution Lambda (smallest power of two >= scale) and clamped to
    [-bound, bound]. The default bound is 2^46 * max(Lambda, 1): Mironov's
    analysis wants B/Lambda bounded (the clamp is part of the mechanism),
    and the max(..., 1) floor keeps small noise scales from shrinking the
    representable release range below realistic aggregates. Callers whose
    releases can legitimately exceed ~7e13 must pass an explicit bound;
    inputs that the clamp actually truncates raise a UserWarning.
    """
    if scale <= 0:
        raise ValueError("scale must be positive")
    vals = np.asarray(values, dtype=np.float64)
    # ascontiguousarray promotes 0-d to 1-d: keep the true shape.
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    if bound is None:
        lam = 2.0**np.ceil(np.log2(scale))
        bound = float(max(lam, 1.0) * 2.0**46)
    if flat.size and float(np.max(np.abs(flat))) > bound:
        import warnings
        warnings.warn(
            "snapping_laplace: input magnitude exceeds the clamp bound "
            f"({bound:.3g}); the release is clamped. Pass an explicit "
            "bound sized to the query range.", UserWarning)
    _lib().sn_snapping_laplace(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        flat.size, float(scale), float(bound))
    return out.reshape(shape)


def discrete_laplace(counts, scale: float) -> np.ndarray:
    """Integer release: counts + two-sided-geometric noise of scale
    ``scale`` (decay exp(-1/scale)) — no floating-point noise bits."""
    if scale <= 0:
        raise ValueError("scale must be positive")
    vals = np.asarray(counts, dtype=np.int64)
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    _lib().sn_discrete_laplace(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flat.size, float(scale))
    return out.reshape(shape)


def uniform(n: int) -> np.ndarray:
    """Raw uniforms in (0, 1] from the CSPRNG — for statistical tests."""
    out = np.empty(n, dtype=np.float64)
    _lib().sn_uniform(out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      n)
    return out
