"""Native host library: hardened noise for DP releases.

The on-device (TPU) path draws noise with ``jax.random`` — statistically
correct, and safe for the aggregate pipelines this framework targets, but
a textbook floating-point Laplace leaks information through the noise
sample's low-order bits (Mironov, CCS 2012). The reference delegates its
host noise to the C++ google/differential-privacy library, which hardens
against this; this package is the TPU framework's native twin:

* ``snapping_laplace(values, scale, bound)`` — Mironov's snapping
  mechanism over a ChaCha20 CSPRNG,
* ``discrete_laplace(counts, scale)`` — exact two-sided geometric noise
  for integer releases (no float noise bits at all),
* ``discrete_gaussian(counts, sigma)`` — discrete-Gaussian noise
  (Canonne–Kamath–Steinke sampler) for integer releases; the support is
  exactly the integers, and the acceptance probabilities are realized
  to 2^-53 (double-precision Bernoulli coins) rather than CKS's exact
  rationals — a deviation below any expressible (eps, delta),
* ``secure_gaussian(values, sigma, bound)`` — granularity-snapped
  discrete-Gaussian release for real values (the Gaussian twin of the
  snapping mechanism),
* ``seed(n)`` / ``seed_from_os()`` — deterministic seeding for tests,
  OS entropy otherwise.

The C++ source (``secure_noise.cc``) is compiled on first use with the
toolchain's ``g++`` into a cached shared library next to this file (or
``$PIPELINEDP_TPU_NATIVE_CACHE``). Environments without a compiler get
``NativeUnavailableError`` and callers fall back to the NumPy path —
``ops/noise.py`` documents the resulting security posture.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "secure_noise.cc")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_ERROR: Optional[str] = None


class NativeUnavailableError(RuntimeError):
    """The native library could not be built/loaded on this host."""


def _cache_dir() -> str:
    override = os.environ.get("PIPELINEDP_TPU_NATIVE_CACHE")
    if override:
        os.makedirs(override, exist_ok=True)
        return override
    d = os.path.dirname(__file__)
    return d if os.access(d, os.W_OK) else tempfile.gettempdir()


def _build_shared_lib(src: str, out_name: str) -> str:
    """Compile ``src`` into the cache dir on first use (mtime-checked)."""
    out = os.path.join(_cache_dir(), out_name)
    if (os.path.exists(out) and
            os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeUnavailableError(
            f"g++ failed building {os.path.basename(src)}: "
            f"{proc.stderr[-500:]}")
    os.replace(tmp, out)  # atomic: concurrent builders race harmlessly
    return out


def _build() -> str:
    return _build_shared_lib(_SRC, "_secure_noise.so")


def _lib() -> ctypes.CDLL:
    global _LIB, _LOAD_ERROR
    if _LIB is not None:
        return _LIB
    if _LOAD_ERROR is not None:
        raise NativeUnavailableError(_LOAD_ERROR)
    with _LOCK:
        if _LIB is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(_build())
        except (OSError, NativeUnavailableError) as e:
            _LOAD_ERROR = str(e)
            raise NativeUnavailableError(_LOAD_ERROR) from e
        lib.sn_seed.argtypes = [ctypes.c_uint64]
        lib.sn_seed_from_os.argtypes = []
        lib.sn_snapping_laplace.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_double, ctypes.c_double]
        lib.sn_snapping_laplace.restype = ctypes.c_double
        lib.sn_uniform.argtypes = [ctypes.POINTER(ctypes.c_double),
                                   ctypes.c_int64]
        lib.sn_discrete_laplace.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_double]
        lib.sn_discrete_gaussian.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_double]
        lib.sn_discrete_gaussian.restype = ctypes.c_int32
        lib.sn_secure_gaussian.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_double, ctypes.c_double]
        lib.sn_secure_gaussian.restype = ctypes.c_double
        _LIB = lib
        return _LIB


def available() -> bool:
    """True when the native library can be (or was) built and loaded.
    May spawn a g++ build on first call — use :func:`is_loaded` for a
    side-effect-free check."""
    try:
        _lib()
        return True
    except NativeUnavailableError:
        return False


def is_loaded() -> bool:
    """True iff the library is already loaded in this process. Never
    triggers a build."""
    return _LIB is not None


def seed(n: int) -> None:
    """Deterministic CSPRNG seeding — tests only."""
    _lib().sn_seed(ctypes.c_uint64(n & (2**64 - 1)))


def seed_from_os() -> None:
    """Re-key from OS entropy (e.g. after fork)."""
    _lib().sn_seed_from_os()


def snapping_laplace(values, scale: float,
                     bound: Optional[float] = None) -> np.ndarray:
    """Snapping-Laplace release of ``values`` with noise scale ``scale``.

    Returns values + Laplace(scale) noise, rounded to the snapping
    resolution Lambda (smallest power of two >= scale) and clamped to
    [-bound, bound]. The default bound is 2^46 * max(Lambda, 1): Mironov's
    analysis wants B/Lambda bounded (the clamp is part of the mechanism),
    and the max(..., 1) floor keeps small noise scales from shrinking the
    representable release range below realistic aggregates. Callers whose
    releases can legitimately exceed ~7e13 must pass an explicit bound;
    inputs that the clamp actually truncates raise a UserWarning.
    """
    if scale <= 0:
        raise ValueError("scale must be positive")
    vals = np.asarray(values, dtype=np.float64)
    # ascontiguousarray promotes 0-d to 1-d: keep the true shape.
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    if bound is None:
        lam = 2.0**np.ceil(np.log2(scale))
        bound = float(max(lam, 1.0) * 2.0**46)
    if flat.size and float(np.max(np.abs(flat))) > bound:
        import warnings
        warnings.warn(
            "snapping_laplace: input magnitude exceeds the clamp bound "
            f"({bound:.3g}); the release is clamped. Pass an explicit "
            "bound sized to the query range.", UserWarning)
    _lib().sn_snapping_laplace(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        flat.size, float(scale), float(bound))
    return out.reshape(shape)


def discrete_laplace(counts, scale: float) -> np.ndarray:
    """Integer release: counts + two-sided-geometric noise of scale
    ``scale`` (decay exp(-1/scale)) — no floating-point noise bits."""
    if scale <= 0:
        raise ValueError("scale must be positive")
    vals = np.asarray(counts, dtype=np.int64)
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    _lib().sn_discrete_laplace(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flat.size, float(scale))
    return out.reshape(shape)


def discrete_gaussian(counts, sigma: float) -> np.ndarray:
    """Integer release: counts + discrete-Gaussian noise of standard
    deviation ~``sigma`` (Canonne–Kamath–Steinke sampler) — no
    floating-point noise bits in the RELEASE (the support is exactly
    the integers). The sampler's acceptance coins are double-precision
    Bernoullis, so acceptance probabilities are realized to 2^-53
    rather than CKS's exact rationals (see ``secure_noise.cc``) — the
    distributional deviation is negligible for any expressible
    (eps, delta). ``sigma`` must be in (0, 2^40)."""
    if not 0 < sigma < 2.0**40:
        raise ValueError("sigma must be in (0, 2^40)")
    vals = np.asarray(counts, dtype=np.int64)
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    rc = _lib().sn_discrete_gaussian(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flat.size, float(sigma))
    if rc != 0:
        raise ValueError(f"sn_discrete_gaussian rejected sigma={sigma}")
    return out.reshape(shape)


def secure_gaussian(values, sigma: float,
                    bound: Optional[float] = None) -> np.ndarray:
    """Hardened Gaussian release of ``values`` with noise std ``sigma``:
    the value is snapped to a power-of-two granularity g (sized so
    sigma/g is in (2^39, 2^40]) and g-scaled discrete-Gaussian noise
    (integer-supported; acceptance coins realized to 2^-53 — see
    :func:`discrete_gaussian`) is added, so the release's support is
    the g-grid — the
    Gaussian twin of :func:`snapping_laplace`, replacing the reference's
    PyDP secure GaussianMechanism (reference
    ``pipeline_dp/dp_computations.py:127-143``). Same default clamp
    bound policy as the snapping mechanism."""
    if sigma <= 0:
        raise ValueError("sigma must be positive")
    vals = np.asarray(values, dtype=np.float64)
    shape = vals.shape
    flat = np.ascontiguousarray(vals).ravel()
    out = np.empty_like(flat)
    if bound is None:
        lam = 2.0**np.ceil(np.log2(sigma))
        bound = float(max(lam, 1.0) * 2.0**46)
    if flat.size and float(np.max(np.abs(flat))) > bound:
        import warnings
        warnings.warn(
            "secure_gaussian: input magnitude exceeds the clamp bound "
            f"({bound:.3g}); the release is clamped. Pass an explicit "
            "bound sized to the query range.", UserWarning)
    g = _lib().sn_secure_gaussian(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        flat.size, float(sigma), float(bound))
    if g <= 0:
        raise ValueError(f"sn_secure_gaussian rejected sigma={sigma}")
    return out.reshape(shape)


def uniform(n: int) -> np.ndarray:
    """Raw uniforms in (0, 1] from the CSPRNG — for statistical tests."""
    out = np.empty(n, dtype=np.float64)
    _lib().sn_uniform(out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      n)
    return out


# ---------------------------------------------------------------------------
# Ingest acceleration: hash-based integer factorization (encode.cc)
# ---------------------------------------------------------------------------

_ENC_SRC = os.path.join(os.path.dirname(__file__), "encode.cc")
_ENC_LIB: Optional[ctypes.CDLL] = None
_ENC_ERROR: Optional[str] = None


def _enc_lib() -> ctypes.CDLL:
    global _ENC_LIB, _ENC_ERROR
    if _ENC_LIB is not None:
        return _ENC_LIB
    if _ENC_ERROR is not None:
        raise NativeUnavailableError(_ENC_ERROR)
    with _LOCK:
        if _ENC_LIB is not None:
            return _ENC_LIB
        try:
            lib = ctypes.CDLL(_build_shared_lib(_ENC_SRC, "_encode.so"))
        except (OSError, NativeUnavailableError) as e:
            _ENC_ERROR = str(e)
            raise NativeUnavailableError(_ENC_ERROR) from e
        lib.pdp_factorize_i64.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)]
        lib.pdp_factorize_i64.restype = ctypes.c_int64
        _ENC_LIB = lib
        return _ENC_LIB


def encode_available() -> bool:
    """True when the native factorizer can be (or was) built and loaded."""
    try:
        _enc_lib()
        return True
    except NativeUnavailableError:
        return False


def factorize_i64(arr: np.ndarray):
    """``np.unique(arr, return_inverse=True)`` for integer arrays, via a
    grow-as-needed open-addressing hash: O(N + U log U) instead of the
    full O(N log N) sort — the ingest hot path when the vocabulary is
    (much) smaller than the data, which keyed DP datasets are. When an
    early sample finds mostly-distinct keys the C++ side bails and this
    falls back to np.unique, which wins that regime. Returns
    (sorted uniques int64, inverse int32); bit-identical to np.unique."""
    arr = np.asarray(arr)
    if (arr.dtype.kind == "u" and arr.size and
            int(arr.max()) > np.iinfo(np.int64).max):
        raise ValueError(
            "factorize_i64: uint64 values above int64 max would wrap; "
            "use np.unique for this input")
    flat = np.ascontiguousarray(arr, dtype=np.int64).ravel()
    n = flat.size
    inverse = np.empty(n, dtype=np.int32)
    uniq = np.empty(n, dtype=np.int64)
    if n == 0:
        return uniq[:0], inverse
    u = _enc_lib().pdp_factorize_i64(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        inverse.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        uniq.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if u == -2:  # mostly-distinct sample: sort-based wins
        nu, ni = np.unique(flat, return_inverse=True)
        return nu, ni.astype(np.int32)
    if u < 0:
        raise NativeUnavailableError(
            "pdp_factorize_i64 failed (allocation or id overflow)")
    return uniq[:u].copy(), inverse
