// Secure host-side noise for DP releases — the native twin of the
// reference's C++ noise hardening (the PyDP/google differential-privacy
// library uses snapping/geometric constructions; see reference
// pipeline_dp/dp_computations.py:111-143 delegating to
// pydp.algorithms.numerical_mechanisms).
//
// Two pieces:
//  * a ChaCha20-based CSPRNG (raw 64-bit blocks -> uniform doubles),
//    seeded from OS entropy by default, explicitly for tests;
//  * the snapping Laplace mechanism (Mironov, "On significance of the
//    least significant bits for differential privacy", CCS 2012):
//        F(x) = clamp_B( round_to_Lambda( clamp_B(x) + b*S*ln(U) ) )
//    with U uniform in (0,1], S a random sign, Lambda the smallest power
//    of two >= b, and round-to-nearest (ties to even) in multiples of
//    Lambda. The rounding destroys the low-order floating-point bits
//    that leak information under a textbook Laplace implementation.
//
// Built as a plain shared library; bound from Python with ctypes
// (pipelinedp_tpu/native/__init__.py). No Python.h dependency.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

namespace {

// ---------------------------------------------------------------------
// ChaCha20 block function (RFC 8439) as a counter-based random stream.
// ---------------------------------------------------------------------

inline uint32_t rotl(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

#define QR(a, b, c, d)                          \
  a += b; d ^= a; d = rotl(d, 16);              \
  c += d; b ^= c; b = rotl(b, 12);              \
  a += b; d ^= a; d = rotl(d, 8);               \
  c += d; b ^= c; b = rotl(b, 7);

struct ChaCha {
  uint32_t state[16];
  uint32_t block[16];
  int used;  // words consumed from the current block

  void init(const uint8_t key[32], uint64_t stream) {
    static const char sigma[17] = "expand 32-byte k";
    std::memcpy(&state[0], sigma, 16);
    std::memcpy(&state[4], key, 32);
    state[12] = 0;  // block counter
    state[13] = 0;
    state[14] = static_cast<uint32_t>(stream);
    state[15] = static_cast<uint32_t>(stream >> 32);
    used = 16;
  }

  void refill() {
    uint32_t x[16];
    std::memcpy(x, state, sizeof(x));
    for (int i = 0; i < 10; i++) {  // 20 rounds
      QR(x[0], x[4], x[8], x[12]);
      QR(x[1], x[5], x[9], x[13]);
      QR(x[2], x[6], x[10], x[14]);
      QR(x[3], x[7], x[11], x[15]);
      QR(x[0], x[5], x[10], x[15]);
      QR(x[1], x[6], x[11], x[12]);
      QR(x[2], x[7], x[8], x[13]);
      QR(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; i++) block[i] = x[i] + state[i];
    if (++state[12] == 0) ++state[13];
    used = 0;
  }

  uint64_t next64() {
    if (used > 14) refill();
    uint64_t lo = block[used++];
    uint64_t hi = block[used++];
    return (hi << 32) | lo;
  }

  // Uniform double in (0, 1]: 53 random mantissa bits, never 0 so ln(U)
  // is finite.
  double uniform01() {
    uint64_t r = next64() >> 11;           // 53 bits
    return (static_cast<double>(r) + 1.0) * 0x1p-53;
  }
};

ChaCha g_rng;
bool g_seeded = false;

void seed_from_os() {
  uint8_t key[32];
  FILE* f = std::fopen("/dev/urandom", "rb");
  if (f != nullptr) {
    size_t got = std::fread(key, 1, sizeof(key), f);
    std::fclose(f);
    if (got == sizeof(key)) {
      g_rng.init(key, /*stream=*/0);
      g_seeded = true;
      return;
    }
  }
  // Last resort (no /dev/urandom): time-derived key. Still ChaCha-mixed.
  uint64_t t = static_cast<uint64_t>(std::clock());
  std::memset(key, 0, sizeof(key));
  std::memcpy(key, &t, sizeof(t));
  g_rng.init(key, 0);
  g_seeded = true;
}

inline void ensure_seeded() {
  if (!g_seeded) seed_from_os();
}

// Smallest power of two >= b (b > 0), as a double.
inline double lambda_for(double b) {
  int exp;
  double frac = std::frexp(b, &exp);  // b = frac * 2^exp, frac in [0.5, 1)
  return (frac == 0.5) ? std::ldexp(1.0, exp - 1) : std::ldexp(1.0, exp);
}

// Round y to the nearest multiple of lambda, ties to even — uses the
// FPU's round-to-nearest-even on y/lambda (exact: lambda is a power of
// two, so the division only shifts the exponent).
inline double round_to(double y, double lambda) {
  return std::nearbyint(y / lambda) * lambda;
}

inline double clamp(double x, double bound) {
  if (x > bound) return bound;
  if (x < -bound) return -bound;
  return x;
}

}  // namespace

extern "C" {

// Deterministic seeding for tests; any 64-bit seed expands into the key.
void sn_seed(uint64_t seed) {
  uint8_t key[32];
  for (int i = 0; i < 4; i++) {
    uint64_t w = seed ^ (0x9E3779B97F4A7C15ull * (i + 1));
    // splitmix64 finalizer per word.
    w ^= w >> 30; w *= 0xBF58476D1CE4E5B9ull;
    w ^= w >> 27; w *= 0x94D049BB133111EBull;
    w ^= w >> 31;
    std::memcpy(key + 8 * i, &w, 8);
  }
  g_rng.init(key, 0);
  g_seeded = true;
}

void sn_seed_from_os() { seed_from_os(); }

// Snapping Laplace: adds noise of scale b to each value in-place-style
// (reads values[i], writes out[i]), clamping to [-bound, bound].
// Returns the snapping resolution Lambda (callers may report it).
double sn_snapping_laplace(const double* values, double* out, int64_t n,
                           double b, double bound) {
  ensure_seeded();
  const double lambda = lambda_for(b);
  for (int64_t i = 0; i < n; i++) {
    uint64_t bits = g_rng.next64();
    double sign = (bits & 1) ? 1.0 : -1.0;
    double u = g_rng.uniform01();
    double y = clamp(values[i], bound) + b * sign * std::log(u);
    out[i] = clamp(round_to(y, lambda), bound);
  }
  return lambda;
}

// Raw uniform doubles in (0, 1] — exposed for statistical tests of the
// underlying stream.
void sn_uniform(double* out, int64_t n) {
  ensure_seeded();
  for (int64_t i = 0; i < n; i++) out[i] = g_rng.uniform01();
}

// Two-sided geometric ("discrete Laplace") noise with decay
// q = exp(-1/b): integer-valued noise for count releases — the release
// has no floating-point noise bits at all. Sampled exactly as the
// difference of two iid geometrics: if G1, G2 ~ Geom(1-q) on {0,1,...}
// then P(G1 - G2 = k) = (1-q)/(1+q) * q^|k|.
void sn_discrete_laplace(const int64_t* values, int64_t* out, int64_t n,
                         double b) {
  ensure_seeded();
  const double log_q = -1.0 / b;
  for (int64_t i = 0; i < n; i++) {
    int64_t g1 = static_cast<int64_t>(
        std::floor(std::log(g_rng.uniform01()) / log_q));
    int64_t g2 = static_cast<int64_t>(
        std::floor(std::log(g_rng.uniform01()) / log_q));
    out[i] = values[i] + (g1 - g2);
  }
}

}  // extern "C"
