// Secure host-side noise for DP releases — the native twin of the
// reference's C++ noise hardening (the PyDP/google differential-privacy
// library uses snapping/geometric constructions; see reference
// pipeline_dp/dp_computations.py:111-143 delegating to
// pydp.algorithms.numerical_mechanisms).
//
// Two pieces:
//  * a ChaCha20-based CSPRNG (raw 64-bit blocks -> uniform doubles),
//    seeded from OS entropy by default, explicitly for tests;
//  * the snapping Laplace mechanism (Mironov, "On significance of the
//    least significant bits for differential privacy", CCS 2012):
//        F(x) = clamp_B( round_to_Lambda( clamp_B(x) + b*S*ln(U) ) )
//    with U uniform in (0,1], S a random sign, Lambda the smallest power
//    of two >= b, and round-to-nearest (ties to even) in multiples of
//    Lambda. The rounding destroys the low-order floating-point bits
//    that leak information under a textbook Laplace implementation.
//
// Built as a plain shared library; bound from Python with ctypes
// (pipelinedp_tpu/native/__init__.py). No Python.h dependency.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

namespace {

// ---------------------------------------------------------------------
// ChaCha20 block function (RFC 8439) as a counter-based random stream.
// ---------------------------------------------------------------------

inline uint32_t rotl(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

#define QR(a, b, c, d)                          \
  a += b; d ^= a; d = rotl(d, 16);              \
  c += d; b ^= c; b = rotl(b, 12);              \
  a += b; d ^= a; d = rotl(d, 8);               \
  c += d; b ^= c; b = rotl(b, 7);

struct ChaCha {
  uint32_t state[16];
  uint32_t block[16];
  int used;  // words consumed from the current block

  void init(const uint8_t key[32], uint64_t stream) {
    static const char sigma[17] = "expand 32-byte k";
    std::memcpy(&state[0], sigma, 16);
    std::memcpy(&state[4], key, 32);
    state[12] = 0;  // block counter
    state[13] = 0;
    state[14] = static_cast<uint32_t>(stream);
    state[15] = static_cast<uint32_t>(stream >> 32);
    used = 16;
  }

  void refill() {
    uint32_t x[16];
    std::memcpy(x, state, sizeof(x));
    for (int i = 0; i < 10; i++) {  // 20 rounds
      QR(x[0], x[4], x[8], x[12]);
      QR(x[1], x[5], x[9], x[13]);
      QR(x[2], x[6], x[10], x[14]);
      QR(x[3], x[7], x[11], x[15]);
      QR(x[0], x[5], x[10], x[15]);
      QR(x[1], x[6], x[11], x[12]);
      QR(x[2], x[7], x[8], x[13]);
      QR(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; i++) block[i] = x[i] + state[i];
    if (++state[12] == 0) ++state[13];
    used = 0;
  }

  uint64_t next64() {
    if (used > 14) refill();
    uint64_t lo = block[used++];
    uint64_t hi = block[used++];
    return (hi << 32) | lo;
  }

  // Uniform double in (0, 1]: 53 random mantissa bits, never 0 so ln(U)
  // is finite.
  double uniform01() {
    uint64_t r = next64() >> 11;           // 53 bits
    return (static_cast<double>(r) + 1.0) * 0x1p-53;
  }
};

ChaCha g_rng;
bool g_seeded = false;

void seed_from_os() {
  uint8_t key[32];
  FILE* f = std::fopen("/dev/urandom", "rb");
  if (f != nullptr) {
    size_t got = std::fread(key, 1, sizeof(key), f);
    std::fclose(f);
    if (got == sizeof(key)) {
      g_rng.init(key, /*stream=*/0);
      g_seeded = true;
      return;
    }
  }
  // Last resort (no /dev/urandom): time-derived key. Still ChaCha-mixed.
  uint64_t t = static_cast<uint64_t>(std::clock());
  std::memset(key, 0, sizeof(key));
  std::memcpy(key, &t, sizeof(t));
  g_rng.init(key, 0);
  g_seeded = true;
}

inline void ensure_seeded() {
  if (!g_seeded) seed_from_os();
}

// Smallest power of two >= b (b > 0), as a double.
inline double lambda_for(double b) {
  int exp;
  double frac = std::frexp(b, &exp);  // b = frac * 2^exp, frac in [0.5, 1)
  return (frac == 0.5) ? std::ldexp(1.0, exp - 1) : std::ldexp(1.0, exp);
}

// Round y to the nearest multiple of lambda, ties to even — uses the
// FPU's round-to-nearest-even on y/lambda (exact: lambda is a power of
// two, so the division only shifts the exponent).
inline double round_to(double y, double lambda) {
  return std::nearbyint(y / lambda) * lambda;
}

inline double clamp(double x, double bound) {
  if (x > bound) return bound;
  if (x < -bound) return -bound;
  return x;
}

// ---------------------------------------------------------------------
// Exact discrete Gaussian (Canonne–Kamath–Steinke, "The Discrete
// Gaussian for Differential Privacy", NeurIPS 2020) — the hardened twin
// of the reference's PyDP GaussianMechanism (reference
// pipeline_dp/dp_computations.py:127-143). Rejection sampling from the
// discrete Laplace via exact Bernoulli(exp(-gamma)) coin flips; every
// Bernoulli uses one fresh 64-bit ChaCha word, so individual coin
// probabilities are realized to 2^-64 (rational gammas) / 2^-53 (the
// one real-valued acceptance gamma) — deviations far below any (eps,
// delta) this framework can express, and crucially the *support* of
// the output is exactly the integers: no floating-point noise bits.
// ---------------------------------------------------------------------

// Bernoulli(num / (den * k)) with num <= den * k, den <= 2^40, k small:
// compare one uniform 64-bit word against the exact rational threshold
// in 128-bit arithmetic (no rounding).
inline bool bern_frac(uint64_t num, uint64_t den, uint64_t k) {
  uint64_t r = g_rng.next64();
  return (static_cast<unsigned __int128>(r) * den) * k <
         (static_cast<unsigned __int128>(num) << 64);
}

// Bernoulli(p) for real p in [0, 1] at 2^-53 resolution.
inline bool bern_p(double p) {
  uint64_t r = g_rng.next64() >> 11;
  return static_cast<double>(r) < p * 0x1p53;
}

// Bernoulli(exp(-u/t)) for 0 <= u <= t (CKS Algorithm 1): run the von
// Neumann series K=1,2,... with Bernoulli(gamma/K) coins; exp(-gamma)
// is the probability K stops odd. The cap at K=64 is unreachable in
// practice (P ~ 1/64!) and breaks toward an odd K.
inline bool bexp_rat(uint64_t u, uint64_t t) {
  uint64_t k = 1;
  while (bern_frac(u, t, k)) {
    if (++k > 64) break;
  }
  return (k & 1) == 1;
}

// Bernoulli(exp(-f)) for real f in [0, 1] — same series, real coins.
inline bool bexp_frac(double f) {
  uint64_t k = 1;
  while (bern_p(f / static_cast<double>(k))) {
    if (++k > 64) break;
  }
  return (k & 1) == 1;
}

// Bernoulli(exp(-gamma)) for real gamma >= 0: exp(-gamma) =
// exp(-1)^floor(gamma) * exp(-frac(gamma)).
inline bool bexp(double gamma) {
  while (gamma > 1.0) {
    if (!bexp_rat(1, 1)) return false;
    gamma -= 1.0;
  }
  return bexp_frac(gamma < 0.0 ? 0.0 : gamma);
}

// Discrete Laplace with integer scale t: P(Y = y) proportional to
// exp(-|y|/t) (CKS Algorithm 2). U is drawn modulo-bias-free.
inline int64_t sample_dlaplace(uint64_t t) {
  for (;;) {
    uint64_t u = 0;
    if (t > 1) {
      const uint64_t lim = UINT64_MAX - UINT64_MAX % t;
      do {
        u = g_rng.next64();
      } while (u >= lim);
      u %= t;
    }
    if (!bexp_rat(u, t)) continue;  // accept U with prob exp(-U/t)
    uint64_t v = 0;  // V ~ Geometric(1 - exp(-1))
    while (bexp_rat(1, 1)) {
      if (++v > 4096) break;  // unreachable (P ~ e^-4096)
    }
    const uint64_t x = u + t * v;
    const bool neg = (g_rng.next64() & 1) != 0;
    if (neg && x == 0) continue;  // don't double-count zero
    return neg ? -static_cast<int64_t>(x) : static_cast<int64_t>(x);
  }
}

// Discrete Gaussian N_Z(0, sigma^2) (CKS Algorithm 3): rejection from
// discrete Laplace of scale t = floor(sigma) + 1; O(1) expected
// iterations independent of sigma.
inline int64_t sample_dgauss(double sigma) {
  const uint64_t t = static_cast<uint64_t>(std::floor(sigma)) + 1;
  const double s2 = sigma * sigma;
  for (;;) {
    const int64_t y = sample_dlaplace(t);
    const double a =
        std::fabs(static_cast<double>(y)) - s2 / static_cast<double>(t);
    if (bexp(a * a / (2.0 * s2))) return y;
  }
}

}  // namespace

extern "C" {

// Deterministic seeding for tests; any 64-bit seed expands into the key.
void sn_seed(uint64_t seed) {
  uint8_t key[32];
  for (int i = 0; i < 4; i++) {
    uint64_t w = seed ^ (0x9E3779B97F4A7C15ull * (i + 1));
    // splitmix64 finalizer per word.
    w ^= w >> 30; w *= 0xBF58476D1CE4E5B9ull;
    w ^= w >> 27; w *= 0x94D049BB133111EBull;
    w ^= w >> 31;
    std::memcpy(key + 8 * i, &w, 8);
  }
  g_rng.init(key, 0);
  g_seeded = true;
}

void sn_seed_from_os() { seed_from_os(); }

// Snapping Laplace: adds noise of scale b to each value in-place-style
// (reads values[i], writes out[i]), clamping to [-bound, bound].
// Returns the snapping resolution Lambda (callers may report it).
double sn_snapping_laplace(const double* values, double* out, int64_t n,
                           double b, double bound) {
  ensure_seeded();
  const double lambda = lambda_for(b);
  for (int64_t i = 0; i < n; i++) {
    uint64_t bits = g_rng.next64();
    double sign = (bits & 1) ? 1.0 : -1.0;
    double u = g_rng.uniform01();
    double y = clamp(values[i], bound) + b * sign * std::log(u);
    out[i] = clamp(round_to(y, lambda), bound);
  }
  return lambda;
}

// Raw uniform doubles in (0, 1] — exposed for statistical tests of the
// underlying stream.
void sn_uniform(double* out, int64_t n) {
  ensure_seeded();
  for (int64_t i = 0; i < n; i++) out[i] = g_rng.uniform01();
}

// Two-sided geometric ("discrete Laplace") noise with decay
// q = exp(-1/b): integer-valued noise for count releases — the release
// has no floating-point noise bits at all. Sampled exactly as the
// difference of two iid geometrics: if G1, G2 ~ Geom(1-q) on {0,1,...}
// then P(G1 - G2 = k) = (1-q)/(1+q) * q^|k|.
void sn_discrete_laplace(const int64_t* values, int64_t* out, int64_t n,
                         double b) {
  ensure_seeded();
  const double log_q = -1.0 / b;
  for (int64_t i = 0; i < n; i++) {
    int64_t g1 = static_cast<int64_t>(
        std::floor(std::log(g_rng.uniform01()) / log_q));
    int64_t g2 = static_cast<int64_t>(
        std::floor(std::log(g_rng.uniform01()) / log_q));
    out[i] = values[i] + (g1 - g2);
  }
}

// Exact discrete Gaussian noise for integer releases (counts): the
// release is an integer — no floating-point noise bits at all. Returns
// 0 on success, -1 for out-of-range sigma (must be in (0, 2^40): the
// exact-rational Bernoulli threshold needs r * t * k < 2^128).
int32_t sn_discrete_gaussian(const int64_t* values, int64_t* out,
                             int64_t n, double sigma) {
  if (!(sigma > 0.0) || sigma >= 0x1p40) return -1;
  ensure_seeded();
  for (int64_t i = 0; i < n; i++) {
    out[i] = values[i] + sample_dgauss(sigma);
  }
  return 0;
}

// Hardened Gaussian for real-valued releases, mirroring the snapping
// Laplace's contract: snap the (clamped) value to a power-of-two
// granularity g and add g * DiscreteGaussian(sigma/g). g is sized so
// sigma/g lands in (2^39, 2^40] (the top end hit exactly when sigma is
// a power of two — sample_dgauss handles t = 2^40 + 1 without 128-bit
// overflow in bern_frac): the output's support is the g-grid
// (for |value| < 2^53 * g; beyond that the double's own ulp > g is the
// effective grid — still power-of-two), so a textbook float Gaussian's
// low-mantissa-bit leakage (Mironov-style) has no channel, while the
// g/2 <= sigma * 2^-41 rounding is far below the noise. Returns g,
// or -1.0 for invalid sigma.
double sn_secure_gaussian(const double* values, double* out, int64_t n,
                          double sigma, double bound) {
  if (!(sigma > 0.0) || !std::isfinite(sigma)) return -1.0;
  ensure_seeded();
  const double g = lambda_for(sigma) * 0x1p-40;  // sigma/g in (2^39, 2^40]
  const double sigma_i = sigma / g;
  for (int64_t i = 0; i < n; i++) {
    const double v = round_to(clamp(values[i], bound), g);
    out[i] = clamp(
        v + g * static_cast<double>(sample_dgauss(sigma_i)), bound);
  }
  return g;
}

}  // extern "C"
