// Host-side integer factorization for the fused-plane encoder.
//
// np.unique(return_inverse=True) sorts all N rows (O(N log N) with a
// full-size permutation); ingest only needs a dense vocabulary, which a
// grow-as-needed open-addressing hash builds in O(N + U log U) for U
// distinct keys (U << N for keyed DP datasets). The unique values are
// returned ASCENDING and the inverse indexes into that sorted order, so
// the result is bit-identical to np.unique — callers can swap freely.
//
// Build: compiled on first use by pipelinedp_tpu/native/__init__.py
// (_build_shared_lib) with the same g++ recipe as secure_noise.cc.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <vector>

namespace {

inline uint64_t fmix64(uint64_t k) {
  k ^= k >> 33;
  k *= 0xff51afd7ed558ccdULL;
  k ^= k >> 33;
  k *= 0xc4ceb9fe1a85ec53ULL;
  k ^= k >> 33;
  return k;
}

struct Table {
  // Parallel arrays: keys_ holds the key, ids_ holds its first-appearance
  // id (-1 = empty slot).
  std::vector<int64_t> keys_;
  std::vector<int32_t> ids_;
  uint64_t mask_ = 0;
  int64_t size_ = 0;

  explicit Table(uint64_t cap_pow2) {
    keys_.resize(cap_pow2);
    ids_.assign(cap_pow2, -1);
    mask_ = cap_pow2 - 1;
  }

  // Returns the id of `key`, inserting with id `next_id` when absent.
  inline int32_t lookup_or_insert(int64_t key, int32_t next_id) {
    uint64_t slot = fmix64(static_cast<uint64_t>(key)) & mask_;
    while (true) {
      int32_t id = ids_[slot];
      if (id == -1) {
        keys_[slot] = key;
        ids_[slot] = next_id;
        ++size_;
        return next_id;
      }
      if (keys_[slot] == key) return id;
      slot = (slot + 1) & mask_;
    }
  }

  bool needs_grow() const {
    return static_cast<uint64_t>(size_) * 10 >= (mask_ + 1) * 7;
  }
};

}  // namespace

extern "C" {

// Factorizes `in[0..n)`: writes sorted unique values to `out_uniq`
// (capacity must be >= number of uniques; n always suffices) and the
// rank of each input among them to `out_inverse[0..n)`. Returns the
// number of uniques, -1 on allocation failure, or -2 when an early
// sample finds mostly-distinct keys — there the table degenerates to
// ~2N cache-missing slots plus an O(N log N) vocabulary sort, and the
// caller's np.unique is the better algorithm.
int64_t pdp_factorize_i64(const int64_t* in, int64_t n,
                          int32_t* out_inverse, int64_t* out_uniq) {
  // Distinctness probe: an eighth of the way in, mostly-new keys imply
  // the degenerate U~N regime. Probing earlier misclassifies
  // moderate vocabularies (a 200k vocab still looks "mostly new" in the
  // first 2^17 rows); probing at n/8 costs at most 12.5% extra work on
  // the bail path.
  const int64_t bail_check_at = (n >> 3) >= (1 << 17) ? (n >> 3) : -1;
  try {
    uint64_t cap = 1 << 10;
    Table table(cap);
    std::vector<int64_t> uniq;  // first-appearance order
    uniq.reserve(1 << 10);
    for (int64_t i = 0; i < n; ++i) {
      if (i == bail_check_at &&
          static_cast<int64_t>(uniq.size()) * 5 > i * 3) {
        return -2;
      }
      if (table.needs_grow()) {
        Table bigger((table.mask_ + 1) * 2);
        for (uint64_t s = 0; s <= table.mask_; ++s) {
          if (table.ids_[s] != -1) {
            bigger.lookup_or_insert(table.keys_[s], table.ids_[s]);
          }
        }
        bigger.size_ = table.size_;
        table = std::move(bigger);
      }
      if (uniq.size() >= 0x7fffffffULL) return -1;  // int32 id overflow
      int32_t next = static_cast<int32_t>(uniq.size());
      int32_t id = table.lookup_or_insert(in[i], next);
      if (id == next) uniq.push_back(in[i]);
      out_inverse[i] = id;  // first-appearance id; remapped below
    }

    // Sort the vocabulary and remap first-appearance ids to sorted ranks.
    const int64_t u = static_cast<int64_t>(uniq.size());
    std::vector<int32_t> order(u);
    for (int64_t i = 0; i < u; ++i) order[i] = static_cast<int32_t>(i);
    std::sort(order.begin(), order.end(),
              [&uniq](int32_t a, int32_t b) { return uniq[a] < uniq[b]; });
    std::vector<int32_t> rank(u);
    for (int64_t r = 0; r < u; ++r) {
      rank[order[r]] = static_cast<int32_t>(r);
      out_uniq[r] = uniq[order[r]];
    }
    for (int64_t i = 0; i < n; ++i) {
      out_inverse[i] = rank[out_inverse[i]];
    }
    return u;
  } catch (...) {
    return -1;
  }
}

}  // extern "C"
