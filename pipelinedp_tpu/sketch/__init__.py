"""Sketch-first ingest + DP heavy hitters: the unbounded-key path.

Removes the last dense-key-universe assumption: when the partition
axis is URLs / queries / user-generated strings (billions of
candidates, power-law mass), the key space is **discovered** through
a two-phase path instead of materialized in HBM:

* phase 1 — a device-resident ``[depth, width]`` counting sketch over
  seeded stable hashes of the keys (one-hot-matmul binning, fed in
  chunks through the ingest ring; per-user contribution bounded
  BEFORE accumulation), then DP candidate selection over the bucket
  masses (Laplace noise via the counter-based generator, budget drawn
  through ``budget_accounting`` with a proper audit record);
* phase 2 — the existing exact dense engine over ONLY the selected
  candidates, via a host-side key→candidate-id table; private
  partition selection and noise run exactly as a dense run.

Entry point: ``DPEngine.aggregate(col, params, extractors,
sketch_first=SketchParams(eps=..., delta=...))``.

Import discipline: this ``__init__`` stays light (hashing + params
only — numpy, no jax) so the blessed stable hash is importable from
anywhere without pulling the engine. The ``sketch-confinement`` lint confines hashing and
candidate-table construction to this package and bans raw ``hash()``
on keys everywhere else.
"""

from pipelinedp_tpu.sketch import hashing
from pipelinedp_tpu.sketch.hashing import (DEFAULT_SEED, bucket_ids,
                                           stable_hash64,
                                           stable_hash_any)
from pipelinedp_tpu.sketch.params import SketchParams

__all__ = [
    "DEFAULT_SEED",
    "SketchParams",
    "bucket_ids",
    "hashing",
    "stable_hash64",
    "stable_hash_any",
]
