"""Two-phase sketch-first DP aggregation: the unbounded-key path.

Every other hot path in this repo assumes the partition axis is dense,
integer-encoded and HBM-resident before pass A runs. This module
removes that assumption: the key space is **discovered**, not given.

Phase 1 — device counting sketch + DP candidate selection:

1. Extract (privacy_id, key) columns; factorize keys on the HOST into
   a distinct-key table (host memory scales with distinct keys; the
   DEVICE never sees a dense key axis — its world is the fixed
   ``[depth, width]`` bucket grid).
2. Bound per-user contribution **before** the sketch: each user keeps
   at most ``L0`` distinct keys, chosen by a deterministic seeded
   tie-break (a pure function of (hash_seed, user, key) — row-order
   and batch-membership invariant), and each kept (user, key) pair
   counts once. One user therefore moves the bucket-mass vector by at
   most ``L0`` in L1.
3. Stream the bounded pairs' bucket ids through the ingest ring
   (``ingest.BackgroundStager`` stages chunk b+1 while the device
   sketches chunk b) into the one-hot-matmul binner
   (``sketch/device.py``).
4. Select buckets: add Laplace noise at scale ``L0/eps`` to the row-0
   bucket masses via the counter-based generator (one draw per bucket,
   pure in (seed, bucket id)). Releasing this whole noisy vector is
   ``eps``-DP (public axis, L1 sensitivity ``L0``); keeping the
   buckets whose noisy mass clears the Laplace-thresholding bound and
   capping at the ``candidate_cap`` largest are post-processing. The
   budget is drawn through a dedicated ``NaiveBudgetAccountant``
   whose finalized ``audit_record`` lands in the obs audit registry
   like every other accountant's.
5. Candidates: the observed distinct keys whose row-0 bucket was
   selected, as a host-side key→candidate-id table
   (``hashing.build_candidate_table`` — phase-2 input, NOT a release).

Phase 2 — the existing exact dense path over candidates only: rows
are filtered to candidate keys and handed to the already-built
``jax_engine.LazyFusedResult`` (budgets were registered on the
engine's accountant at graph-build time, honoring the two-phase
protocol), which runs **private partition selection + noise exactly
as a dense run** over the restricted axis.

Privacy argument (the README carries the long form): the composed
release is (phase-1 bucket set) ∘ (phase-2 standard DP aggregation
conditioned on it). Phase 1 is (eps, delta)-DP by the noisy-vector
argument above. Given a FIXED selected-bucket set B, "rows whose key
hashes into B" is a data-independent per-row filter, and the cap
lives on the *buckets inside the DP mechanism* — removing a user can
never slide other users' keys into or out of the candidate set — so
phase 2 is exactly the dense engine's guarantee on the filtered
dataset. Total cost = sketch budget + engine budget, both audited.

Parity (PARITY row 37): with every populated bucket selected
(generous phase-1 budget, threshold below 1, cap ≥ populated
buckets), the filtered rows ARE the input rows, and phase 2 is
bit-for-bit the dense path under the same engine accountant and seed
— proven on single device and the 8-device mesh in
``tests/test_sketch.py``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pipelinedp_tpu.sketch import device as sketch_device
from pipelinedp_tpu.sketch import hashing
from pipelinedp_tpu.sketch.params import SketchParams

#: fold_in tag of the phase-1 selection noise stream — distinct from
#: every stream the fused kernel derives from the same root key.
_SELECT_STREAM_TAG = 0x5EC7


def _extract_columns(col, data_extractors
                     ) -> Tuple[np.ndarray, np.ndarray,
                                Optional[np.ndarray]]:
    """(privacy_ids, partition_keys, values|None) as host arrays, from
    an ArrayDataset or extractor-driven rows. Privacy ids are required
    — phase-1 bounding is per privacy unit."""
    from pipelinedp_tpu.jax_engine import ArrayDataset

    if isinstance(col, ArrayDataset):
        if col.privacy_ids is None:
            raise ValueError(
                "sketch-first needs privacy ids: phase-1 contribution "
                "bounding is per privacy unit")
        return (np.asarray(col.privacy_ids),
                np.asarray(col.partition_keys),
                (np.asarray(col.values)
                 if col.values is not None else None))
    pid_ex = data_extractors.privacy_id_extractor
    pk_ex = data_extractors.partition_extractor
    val_ex = data_extractors.value_extractor
    if pid_ex is None:
        raise ValueError(
            "sketch-first needs privacy ids: set a privacy_id_extractor")
    pids, pks, vals = [], [], []
    for row in col:
        pids.append(pid_ex(row))
        pks.append(pk_ex(row))
        vals.append(val_ex(row) if val_ex else 0.0)
    return (np.asarray(pids), np.asarray(pks),
            np.asarray(vals, dtype=np.float64))


def _factorize_keys(pk_arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(distinct keys, int inverse) — the host key table. Sortable
    dtypes go through the vectorized factorizers (ascending order,
    matching phase 2's encode); object keys fall back to np.unique."""
    from pipelinedp_tpu import jax_engine as je

    fac = je._int_factorize(pk_arr)
    if fac is not None:
        return fac
    return je._unique_inverse(pk_arr)


#: Seed tweak separating the privacy-id hash stream from the key
#: hash stream (both derive from SketchParams.hash_seed).
_PID_HASH_SALT = 0x71D5A17


def bound_pairs(pid_arr: np.ndarray, key_inv: np.ndarray,
                key_hashes: np.ndarray, l0: int,
                hash_seed: int) -> np.ndarray:
    """Per-user bounded distinct (user, key) pairs, BEFORE the sketch.

    Returns the key indices (into the distinct-key table) of the kept
    pairs: each (user, key) pair appears once, and each user keeps at
    most ``l0`` keys — the ones with the smallest deterministic
    tie-break ``mix64(key_hash ^ mix64(content_hash(pid) ^ seed))``.

    The user identity in both the dedup and the tie-break salt is the
    CONTENT hash of the privacy id (``hashing.stable_hash64``), never
    a dataset-relative factorized rank: a rank shifts when another
    user is added or removed, which would reshuffle every later
    user's kept-key sample and void the L1 ≤ l0 sensitivity bound
    the Laplace scale is calibrated against. With content-derived
    salts, one user's presence changes ONLY that user's ≤ l0 pairs —
    for any pid dtype — and the kept set is invariant to row order,
    (user, key) duplication and batch membership.
    """
    with np.errstate(over="ignore"):
        seed64 = np.uint64(hash_seed & ((1 << 64) - 1))
        pid_hash = hashing.stable_hash64(pid_arr,
                                         seed=hash_seed ^ _PID_HASH_SALT)
    k_all = key_inv.astype(np.int64)
    # Dedup (user, key) pairs on (content hash, key idx). A 64-bit
    # pid-hash collision merges two users (≈ n^2 / 2^64 — negligible,
    # and it only ever REMOVES pairs: conservative).
    order0 = np.lexsort((k_all, pid_hash))
    ph = pid_hash[order0]
    kv = k_all[order0]
    if len(ph) == 0:
        return np.zeros(0, np.int64)
    first_pair = np.r_[True, (ph[1:] != ph[:-1]) | (kv[1:] != kv[:-1])]
    p_u = ph[first_pair]
    k_u = kv[first_pair]
    with np.errstate(over="ignore"):
        user_salt = hashing.mix64(p_u ^ seed64)
        tb = hashing.mix64(key_hashes[k_u] ^ user_salt)
    order = np.lexsort((tb, p_u))
    sorted_p = p_u[order]
    new_group = np.r_[True, sorted_p[1:] != sorted_p[:-1]]
    first = np.flatnonzero(new_group)
    group_start = np.repeat(first, np.diff(np.r_[first, len(sorted_p)]))
    rank = np.arange(len(sorted_p)) - group_start
    return k_u[order][rank < l0]


def _accumulate_stream(pair_buckets: np.ndarray, width: int,
                       backend: str, chunk_rows: int, tr, mesh=None
                       ) -> Tuple[np.ndarray, int]:
    """Stream the bounded pairs' bucket ids through the ingest ring
    into the device sketch: the stager device_puts chunk b+1 while the
    dispatch thread runs chunk b's binner. Returns ([depth, width]
    int64 host counts, chunks). Exact for any chunking (integer sum).

    With a multi-device ``mesh`` each chunk's row axis shards over the
    devices and the binner runs through
    ``sketch_device.sharded_sketch_chunk_program`` — the local exact-
    integer sketches combine through the topology-aware exchange, so
    the totals are bit-identical to the single-device stream and the
    sketch phase no longer serializes on one chip."""
    from pipelinedp_tpu import ingest, obs
    from pipelinedp_tpu.resilience import faults

    depth = pair_buckets.shape[0]
    n = pair_buckets.shape[1]
    total = np.zeros((depth, width), np.int64)
    n_chunks = max(1, -(-n // chunk_rows))
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    sharded = n_dev > 1
    if sharded:
        from pipelinedp_tpu.parallel import sharded as psh
        row_sharding = psh.NamedSharding(
            mesh, psh.PSpec(None, mesh.axis_names[0]))
        obs.event("sketch.sharded", devices=n_dev,
                  topology=psh.topology_of(mesh).mode)

    def gen_factory(cancelled):
        def gen():
            for b in range(n_chunks):
                lo = b * chunk_rows
                hi = min(n, lo + chunk_rows)
                with tr.span("sketch.stage", cat="sketch", batch=b):
                    chunk = sketch_device.pad_chunk(
                        np.ascontiguousarray(pair_buckets[:, lo:hi]),
                        n_shards=n_dev)
                    if sharded:
                        dev = psh.put_global(chunk, row_sharding)
                    else:
                        dev = jax.device_put(chunk)
                yield b, dev
        return gen()

    with ingest.BackgroundStager(gen_factory, name="sketch-stager") as st:
        for b, dev in st.items():
            faults.check_sketch_chunk(b)
            with tr.span("sketch.accumulate", cat="sketch", batch=b):
                with obs.device_annotation("pdp.sketch_chunk"):
                    if sharded:
                        out = sketch_device.sharded_sketch_chunk_program(
                            width, backend, mesh, dev)
                        if mesh.is_multi_process:
                            # Replicated output: every device holds the
                            # full [depth, width] sketch — read this
                            # process's copy (the global view is not
                            # host-addressable across processes).
                            out = out.addressable_shards[0].data
                    else:
                        out = sketch_device.sketch_chunk_program(
                            dev, width=width, backend=backend)
                sketch_device.accumulate_chunk(total, out)
    return total, n_chunks


def select_buckets(counts_row0: np.ndarray, spec, l0: int, cap: int,
                   threshold: Optional[float], sel_key
                   ) -> Tuple[np.ndarray, float, float]:
    """DP bucket selection over the row-0 sketch masses.

    Releases (internally) the noisy-mass vector ``counts + Lap(l0 /
    spec.eps)`` — one counter-keyed draw per bucket — then keeps the
    buckets clearing the threshold, capped at the ``cap`` largest by
    noisy mass (deterministic stable order). Returns (keep mask
    [width] bool, threshold, noise scale).
    """
    from pipelinedp_tpu.ops import counter_rng
    from pipelinedp_tpu.ops import partition_selection as ps_ops

    width = len(counts_row0)
    scale = l0 / spec.eps
    if threshold is None:
        if spec.delta and spec.delta > 0:
            threshold = ps_ops.LaplaceThresholdingPartitionStrategy(
                spec.eps, spec.delta, l0).threshold
        else:
            threshold = 1.0
    idx = jnp.arange(width, dtype=jnp.uint32)
    unit = counter_rng.laplace(sel_key, idx, jnp.zeros_like(idx))
    noisy = (counts_row0.astype(np.float64) +
             np.asarray(unit, dtype=np.float64) * scale)
    keep = noisy >= threshold
    n_keep = int(keep.sum())
    if n_keep > cap:
        kept_idx = np.flatnonzero(keep)
        order = np.argsort(-noisy[kept_idx], kind="stable")
        winners = kept_idx[order[:cap]]
        keep = np.zeros(width, dtype=bool)
        keep[winners] = True
    return keep, float(threshold), float(scale)


def count_min_estimate(counts: np.ndarray,
                       buckets_of_key: np.ndarray) -> np.ndarray:
    """Count-min mass estimates for keys: min over depth rows of their
    bucket masses (diagnostic only — never released; collisions only
    inflate, so the min over independent rows tightens the estimate)."""
    depth = counts.shape[0]
    est = counts[0][buckets_of_key[0]]
    for d in range(1, depth):
        est = np.minimum(est, counts[d][buckets_of_key[d]])
    return est


class LazySketchFirstResult:
    """Iterable of (partition_key, MetricsTuple): phase 1 (sketch + DP
    candidate selection) runs on first iteration — after
    ``compute_budgets()``, like every lazy result — then phase 2 is
    the inner dense ``LazyFusedResult`` over the candidate-filtered
    rows. Iterating again reuses the cached output."""

    def __init__(self, col, params, sketch_params: SketchParams,
                 data_extractors, inner, rng_seed: Optional[int],
                 mesh=None):
        self._col = col
        self._params = params
        self._sketch = sketch_params
        self._extractors = data_extractors
        self._inner = inner
        self._rng_seed = rng_seed
        self._mesh = mesh
        self._cache: Optional[List] = None
        #: Host-side key→candidate-id encoding table of the last run —
        #: phase-2 INPUT, not a DP release: do not publish it.
        self._candidate_table: Optional[Dict[Any, int]] = None
        #: phase timing totals (sketch_* keys) merged with the inner
        #: result's timings after execution.
        self.timings: Optional[Dict[str, float]] = None

    def __iter__(self):
        if self._cache is None:
            self._cache = self._execute()
        yield from self._cache

    def _execute(self) -> List:
        from pipelinedp_tpu import obs
        from pipelinedp_tpu.budget_accounting import NaiveBudgetAccountant
        from pipelinedp_tpu.aggregate_params import MechanismType
        from pipelinedp_tpu.jax_engine import ArrayDataset
        from pipelinedp_tpu.obs import audit as obs_audit
        from pipelinedp_tpu.ops import noise as noise_ops

        sp = self._sketch
        tr = obs.run_tracer()
        obs.monitor.maybe_start()
        width = sp.resolved_width()
        depth = sp.resolved_depth()
        cap = sp.resolved_candidate_cap()
        backend = sp.resolved_backend()
        l0 = sp.resolved_l0(self._params)

        with tr.span("sketch.extract", cat="sketch"):
            pid_arr, pk_arr, values_arr = _extract_columns(
                self._col, self._extractors)
        with tr.span("sketch.hash", cat="sketch"):
            uniq_keys, key_inv = _factorize_keys(pk_arr)
            key_hashes = hashing.stable_hash64(uniq_keys, sp.hash_seed)
            buckets_of_key = hashing.bucket_ids(key_hashes, width, depth,
                                                sp.hash_seed)
        with tr.span("sketch.bound", cat="sketch"):
            kept_keys = bound_pairs(pid_arr, key_inv, key_hashes, l0,
                                    sp.hash_seed)
            pair_buckets = np.ascontiguousarray(
                buckets_of_key[:, kept_keys])
        counts, n_chunks = _accumulate_stream(
            pair_buckets, width, backend, sp.chunk_rows, tr,
            mesh=self._mesh)

        with tr.span("sketch.select", cat="sketch"):
            # Phase 1's own books: a dedicated accountant whose
            # finalized audit record reaches the obs registry exactly
            # like the engine accountant's — the run report's privacy
            # section then shows BOTH sides of the two-phase cost.
            acc = NaiveBudgetAccountant(total_epsilon=sp.eps,
                                        total_delta=sp.delta)
            spec = acc.request_budget(
                mechanism_type=MechanismType.GENERIC,
                metric="sketch_candidate_selection")
            acc.compute_budgets()
            seed = (self._rng_seed if self._rng_seed is not None else
                    int(noise_ops._host_rng.integers(0, 2**31 - 1)))
            # lint: disable=rng-purity(seed protocol root key for the sketch selection stream, pure in rng_seed)
            root = jax.random.PRNGKey(seed)
            # lint: disable=rng-purity(single stream split, not a per-element schedule; pure in (seed, tag))
            sel_key = jax.random.fold_in(root, _SELECT_STREAM_TAG)
            keep_mask, threshold, noise_scale = select_buckets(
                counts[0], spec, l0, cap, sp.threshold, sel_key)

        with tr.span("sketch.candidates", cat="sketch"):
            key_selected = keep_mask[buckets_of_key[0]]
            candidates, table = hashing.build_candidate_table(
                uniq_keys, key_selected)
            self._candidate_table = table
            row_mask = key_selected[key_inv]

        populated = int((counts[0] > 0).sum())
        obs.inc("sketch.runs")
        obs.event("sketch.selected",
                  buckets_populated=populated,
                  buckets_selected=int(keep_mask.sum()),
                  candidates=len(candidates),
                  universe_keys=int(len(uniq_keys)))
        if obs_audit.audit_enabled():
            # Count-min mass of the CANDIDATE keys only (an estimate
            # over unselected keys would misstate the funnel), and
            # only when the record is actually captured — the
            # O(universe x depth) gather is audit-tier work.
            cand_est = count_min_estimate(
                counts, buckets_of_key[:, key_selected])
            obs_audit.record_sketch({
                "width": width, "depth": depth, "candidate_cap": cap,
                "backend": backend, "l0": l0,
                "eps": spec.eps, "delta": spec.delta,
                "noise_scale": noise_scale, "threshold": threshold,
                "hash_seed_fixed": sp.hash_seed != hashing.DEFAULT_SEED,
                "pairs_sketched": int(pair_buckets.shape[1]),
                "chunks": int(n_chunks),
                "buckets_populated": populated,
                "buckets_selected": int(keep_mask.sum()),
                "universe_keys": int(len(uniq_keys)),
                "candidates": len(candidates),
                "candidate_mass_estimate_max": (int(cand_est.max())
                                                if len(cand_est) else 0),
            })

        self.timings = {
            "sketch_hash_s": tr.total("sketch.hash"),
            "sketch_bound_s": tr.total("sketch.bound"),
            "sketch_accumulate_s": tr.total("sketch.accumulate"),
            "sketch_select_s": tr.total("sketch.select"),
            "sketch_chunks": n_chunks,
            "sketch_candidates": len(candidates),
        }
        if not candidates:
            # Nothing cleared DP selection: release nothing. The inner
            # result stays unexecuted (its registered budget was spent
            # by the accountant split regardless — conservative).
            obs.event("sketch.empty_selection")
            return []

        # Phase 2: the exact dense path over ONLY the candidates. The
        # filtered columns re-encode from scratch inside the inner
        # result, so the factorization (and with it every noise
        # assignment) is exactly what a dense run over these rows
        # would compute — the parity contract's foundation.
        filtered = ArrayDataset(
            privacy_ids=pid_arr[row_mask],
            partition_keys=pk_arr[row_mask],
            values=(values_arr[row_mask]
                    if values_arr is not None else None))
        self._inner.rebind_rows(filtered)
        out = list(self._inner)
        if self._inner.timings:
            self.timings.update(self._inner.timings)
        return out


def build_sketch_first_aggregation(col, params, data_extractors,
                                   sketch_params: SketchParams,
                                   budget_accountant, report_gen,
                                   rng_seed=None, mesh=None,
                                   checkpoint=None, ingest_executor=None,
                                   stream_cache=None
                                   ) -> LazySketchFirstResult:
    """Engine entry for the sketch-first path: registers the phase-2
    budgets on the ENGINE accountant now (graph-build time — the
    two-phase protocol), records the report stages, and returns the
    lazy two-phase result. Phase 1 draws its own (eps, delta) from a
    dedicated accountant at execution time."""
    from pipelinedp_tpu import jax_engine

    report_gen.add_stage(
        f"Sketch phase: per-user bounded (≤ "
        f"{sketch_params.max_buckets_contributed or 'L0'} distinct "
        f"keys) counting sketch over hashed keys; DP bucket selection "
        f"(Laplace, sketch budget eps={sketch_params.eps}, "
        f"delta={sketch_params.delta}) chooses candidate buckets; the "
        "exact dense pass below runs over candidate keys only.")
    inner = jax_engine.build_fused_aggregation(
        col, params, data_extractors, None, budget_accountant,
        report_gen, rng_seed=rng_seed, mesh=mesh, checkpoint=checkpoint,
        ingest_executor=ingest_executor, stream_cache=stream_cache)
    return LazySketchFirstResult(col, params, sketch_params,
                                 data_extractors, inner,
                                 rng_seed=rng_seed, mesh=mesh)
