"""Device-resident counting sketch: one-hot-matmul binning, no scatter.

The sketch is a ``[depth, width]`` int32 count matrix over hashed
bucket ids. The obvious lowering — one masked scatter-add per depth
row — is exactly the gather/scatter traffic the pass-B binner
(``ops/kernels/hist.py``) was built to avoid, so the default backend
here reuses that kernel's idiom: factor each bucket id into radix
digits ``(hi, lo) = (b // 256, b % 256)`` and count bin ``(hi, lo)``
as the MXU contraction ``onehot_hi @ onehot_lo^T`` over a row block —
two one-hot factors, one matmul, the whole ``[W1, 256]`` product
reshaping to the width axis. Per row block every product is 0/1 and
every partial sum is bounded by the block width (512 < 2^24), so the
f32 MXU arithmetic is exact integer arithmetic and the matmul path is
**bit-identical** to the XLA scatter reference (``backend="xla"``) —
the on/off parity the ``sketch_backend`` knob stands on (PARITY row
36, asserted in ``tests/test_sketch.py``).

Padding rows carry bucket id ``-1``: ``-1 // 256 == -1`` matches no
``hi`` one-hot column (and the scatter path masks them explicitly),
so masking is free, exactly like the hist kernel's ``kept``
predicate.

Chunked accumulation is exact (integer sums associate), so the
streamed loop in ``sketch/engine.py`` can feed any batch sizing
through this kernel and land on the same counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu.obs.costs import instrumented_jit

#: Rows per one-hot block: keeps each [W1, R] x [R, 256] contraction's
#: partial sums exact in f32 (R <= 512 < 2^24) and the transient
#: one-hot factors small.
ROW_BLOCK = 512

_LO = 256  # the radix low digit — see sketch.params.WIDTH_MULTIPLE


def _counts_matmul(buckets: jnp.ndarray, width: int) -> jnp.ndarray:
    """[width] int32 bucket counts of one depth row via the radix
    one-hot contraction; ``buckets`` is [n] int32, padded with -1,
    ``n`` a multiple of ROW_BLOCK, ``width`` a multiple of 256."""
    w1 = width // _LO
    blocks = buckets.reshape(-1, ROW_BLOCK)
    iota_hi = jax.lax.broadcasted_iota(jnp.float32, (w1, ROW_BLOCK), 0)
    iota_lo = jax.lax.broadcasted_iota(jnp.float32, (_LO, ROW_BLOCK), 0)

    def body(acc, blk):
        # Integer divmod FIRST, one small-value f32 cast after — the
        # same exactness ordering as the hist kernel: hi < w1 < 2^24
        # casts exactly, and -1 (padding) matches no iota column.
        hi = (blk // _LO).astype(jnp.float32)
        lo = (blk % _LO).astype(jnp.float32)
        oh_hi = jnp.where(hi[None, :] == iota_hi, 1.0, 0.0)  # [w1, R]
        oh_lo = jnp.where(lo[None, :] == iota_lo, 1.0, 0.0)  # [256, R]
        part = jax.lax.dot_general(
            oh_hi, oh_lo, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [w1, 256], exact
        return acc + part.astype(jnp.int32).reshape(width), None

    acc0 = jnp.zeros(width, jnp.int32)
    acc, _ = jax.lax.scan(body, acc0, blocks)
    return acc


def _counts_scatter(buckets: jnp.ndarray, width: int) -> jnp.ndarray:
    """The XLA scatter-add reference lowering (bit-parity twin)."""
    ok = buckets >= 0
    idx = jnp.where(ok, buckets, 0)
    ones = jnp.where(ok, 1, 0).astype(jnp.int32)
    return jnp.zeros(width, jnp.int32).at[idx].add(ones)


def _sketch_chunk(buckets, width: int, backend: str) -> jnp.ndarray:
    """[depth, width] int32 counts of one chunk; ``buckets`` is
    [depth, n] int32 with -1 padding, ``n`` a multiple of ROW_BLOCK.
    ``backend`` rides in static so a knob flip re-traces (jit caches by
    signature) and the cost observatory keys the two programs apart."""
    fn = _counts_matmul if backend == "matmul" else _counts_scatter
    return jnp.stack([fn(buckets[d], width)
                      for d in range(buckets.shape[0])])


#: Instrumented entry (phase ``sketch``): every sketch accumulation
#: compiles through the device-cost observatory, so the run report's
#: ``device_costs`` section carries the binner's roofline verdict.
sketch_chunk_program = instrumented_jit(
    phase="sketch", static_argnames=("width", "backend"))(_sketch_chunk)


def pad_chunk(buckets: np.ndarray, n_shards: int = 1) -> np.ndarray:
    """Pad a [depth, n] host chunk to a ROW_BLOCK multiple with -1
    rows (matched by neither backend) so every chunk shares a jit
    signature per (depth, padded-n) pair. With ``n_shards`` > 1 the
    padded length is a multiple of ``n_shards * ROW_BLOCK``, so every
    mesh shard's row slice is itself ROW_BLOCK-aligned."""
    depth, n = buckets.shape
    unit = ROW_BLOCK * max(1, int(n_shards))
    n_pad = max(-(-n // unit) * unit, unit)
    if n_pad == n:
        return buckets
    out = np.full((depth, n_pad), -1, dtype=np.int32)
    out[:, :n] = buckets
    return out


@instrumented_jit(phase="sketch", static_argnames=("width", "backend",
                                                   "mesh"))
def sharded_sketch_chunk_program(width: int, backend: str, mesh,
                                 buckets):
    """Mesh twin of ``sketch_chunk_program``: the chunk's row axis
    shards over the mesh, each device bins its slice through the SAME
    per-backend chunk body, and the local [depth, width] exact-integer
    sketches combine through ``parallel.sharded``'s one exchange
    policy (owner-block width scatter on a single-controller mesh —
    width is a 256 multiple, so any power-of-two mesh tiles it — a
    replicating psum on a multi-process mesh, two-stage under a
    hierarchical topology). Integer sums associate, so the sharded
    accumulation is BIT-IDENTICAL to the single-device scan for any
    mesh size — the phase-1 ceiling removal rides on the same parity
    argument as the pass-A kernels (PARITY row 43)."""
    from pipelinedp_tpu.parallel import sharded as psh

    axis = mesh.axis_names[0]
    topo = psh.topology_of(mesh)
    multiproc = mesh.is_multi_process

    def local_fn(buckets):
        local = _sketch_chunk(buckets, width, backend)
        return psh.combine_shards(local, axis, 1, multiproc, topo=topo)

    row_shard = psh.PSpec(None, axis)
    mapped = psh.shard_map(
        local_fn, mesh=mesh, in_specs=(row_shard,),
        out_specs=psh.PSpec() if multiproc else psh.PSpec(None, axis),
        **{psh._CHECK_KW: False})
    return mapped(buckets)


def accumulate_chunk(total: np.ndarray, device_counts) -> None:
    """Fold one chunk's device counts into the host int64 accumulator
    (in place). Exact: integer sums associate, so any chunking lands
    on the same totals."""
    total += np.asarray(device_counts).astype(np.int64)
