"""Seeded, process-stable key hashing — the ONE blessed hash for keys.

Everything upstream of the counting sketch depends on one property the
builtin ``hash()`` cannot provide: the bucket of a key must be a pure
function of ``(key bytes, seed)`` — identical across processes, runs,
checkpoint resumes and the host/driver boundary. Python's builtin
string hash is salted per process (``PYTHONHASHSEED``), so a resumed
run (or a multi-process mesh) would scatter the same key into
different buckets and every sketch-derived artifact — selected
buckets, candidate tables, released key sets — would silently stop
replaying. The ``sketch-confinement`` lint therefore bans raw
``hash()`` on keys everywhere outside this module; key hashing routes
through :func:`stable_hash64`.

Construction: FNV-1a 64-bit over the key's code units (UTF-32 code
points for ``str``, raw bytes for ``bytes``, the 64-bit value for
integers), seed folded into the offset basis, finished with the
splitmix64 avalanche (:func:`mix64`). The same arithmetic runs
vectorized over NumPy ``<U``/``S``/integer arrays and scalar over
Python objects, so a key hashes identically no matter which container
carried it — asserted in ``tests/test_sketch.py``. Only TRAILING NUL
code units are treated as padding (NumPy pads fixed-width string
cells with NULs, and the hash must not depend on the array's
itemsize — note NumPy itself cannot represent a trailing NUL in
``U``/``S`` cells); embedded and leading NULs are key content and
hash, and the true length is mixed in at the end so prefixes stay
distinct.

Per-depth sketch rows derive their bucket ids by remixing the one
64-bit key hash with a depth salt (:func:`bucket_ids`) — one hash pass
per key, ``depth`` cheap remixes.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import numpy as np

#: Deterministic default seed: sketch artifacts must replay across
#: runs unless the caller explicitly rotates the seed
#: (``SketchParams.hash_seed``).
DEFAULT_SEED = 0x5EEDC0DE

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_GOLDEN = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def mix64(x: Union[int, np.ndarray]) -> np.ndarray:
    """splitmix64 finalizer (Steele et al.), vectorized: a full-period
    avalanche on uint64 — every output bit depends on every input bit,
    which is what lets one key hash feed ``depth`` independent-looking
    bucket rows."""
    with np.errstate(over="ignore"):
        x = np.asarray(x, dtype=np.uint64)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _seed_basis(seed: int) -> np.uint64:
    return mix64(np.uint64((_FNV_OFFSET ^ (seed & _MASK64)) & _MASK64))


def _fnv_rows(mat: np.ndarray, seed: int) -> np.ndarray:
    """Vectorized FNV-1a over the code-unit columns of ``mat`` [N, L]
    (uint8 bytes or uint32 code points). Only TRAILING NUL columns are
    skipped per row — they are NumPy's fixed-width padding, and the
    hash must not depend on the array's itemsize. Embedded/leading
    NULs are key content and DO hash (``a\\0b`` != ``ab``); the true
    (padding-free) length is mixed in at the end."""
    n, width = mat.shape
    h = np.full(n, _seed_basis(seed), dtype=np.uint64)
    nonzero = mat != 0
    any_nz = nonzero.any(axis=1)
    # true length = 1 + index of the last nonzero unit (0 if none).
    true_len = np.where(any_nz,
                        width - np.argmax(nonzero[:, ::-1], axis=1),
                        0).astype(np.uint64)
    prime = np.uint64(_FNV_PRIME)
    with np.errstate(over="ignore"):
        for j in range(width):
            col = mat[:, j].astype(np.uint64)
            live = np.uint64(j) < true_len
            upd = (h ^ col) * prime
            h = np.where(live, upd, h)
        h = h ^ (true_len * np.uint64(_GOLDEN))
    return mix64(h)


def _fnv_scalar(units, seed: int) -> int:
    """Scalar twin of :func:`_fnv_rows` — byte-for-byte the same
    arithmetic, so a Python ``str`` hashes identically to the same
    string inside a NumPy ``<U`` array. Like the array form, trailing
    NULs are treated as padding (NumPy cannot represent them either),
    embedded/leading NULs hash as content."""
    true_len = 0
    for i, u in enumerate(units):
        if u != 0:
            true_len = i + 1
    h = int(_seed_basis(seed))
    for u in units[:true_len]:
        h = ((h ^ u) * _FNV_PRIME) & _MASK64
    h = h ^ ((true_len * _GOLDEN) & _MASK64)
    return int(mix64(np.uint64(h)))


def _hash_int_array(arr: np.ndarray, seed: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = arr.astype(np.int64).astype(np.uint64)
        return mix64(x ^ _seed_basis(seed))


def stable_hash_any(key: Any, seed: int = DEFAULT_SEED) -> int:
    """Seeded stable 64-bit hash of ONE key (str / bytes / int /
    anything with a stable ``repr``). The scalar entry point for
    non-vectorized callers;
    agrees with :func:`stable_hash64` element-wise. NOTE: hashes by
    VALUE BYTES (repr for arbitrary objects) — not by ``__eq__``; use
    it for replayable key→bucket maps, never where object-equality
    semantics must be honored (that is builtin ``hash()``'s job)."""
    if isinstance(key, (bool, np.bool_)):
        key = int(key)
    if isinstance(key, (int, np.integer)):
        with np.errstate(over="ignore"):
            x = np.uint64(int(key) & _MASK64)
            return int(mix64(x ^ _seed_basis(seed)))
    if isinstance(key, str):
        return _fnv_scalar([ord(c) for c in key], seed)
    if isinstance(key, (bytes, bytearray, np.bytes_)):
        return _fnv_scalar(list(bytes(key)), seed)
    return stable_hash_any(repr(key), seed)


def stable_hash64(keys, seed: int = DEFAULT_SEED) -> np.ndarray:
    """Seeded stable uint64 hashes for a key column.

    Accepts NumPy integer / ``<U`` / ``S`` arrays (vectorized) or any
    sequence of str/bytes/int/objects (scalar loop over *unique-ish*
    inputs — callers factorize first, so the loop runs over distinct
    keys, not rows). Same key, same seed → same hash, regardless of
    container.
    """
    arr = keys if isinstance(keys, np.ndarray) else np.asarray(keys)
    if arr.dtype.kind in "iub":
        return _hash_int_array(arr, seed)
    if arr.dtype.kind == "U":
        # UTF-32 code points, native byte order: [N, L] uint32 view.
        a = np.ascontiguousarray(arr)
        if a.size == 0:
            return np.zeros(0, np.uint64)
        L = a.dtype.itemsize // 4
        mat = a.view(np.uint32).reshape(len(a), L)
        if not a.dtype.isnative:  # pragma: no cover - exotic input
            mat = mat.byteswap()
        return _fnv_rows(mat, seed)
    if arr.dtype.kind == "S":
        a = np.ascontiguousarray(arr)
        if a.size == 0:
            return np.zeros(0, np.uint64)
        mat = a.view(np.uint8).reshape(len(a), a.dtype.itemsize)
        return _fnv_rows(mat, seed)
    return np.fromiter((stable_hash_any(k, seed) for k in arr),
                       dtype=np.uint64, count=len(arr))


def bucket_ids(hashes: np.ndarray, width: int, depth: int,
               seed: int = DEFAULT_SEED) -> np.ndarray:
    """[depth, N] int32 bucket rows from one uint64 hash column: row
    ``d`` remixes the key hash with a (seed, d) salt and reduces mod
    ``width``. Row 0 is the SELECTION row (candidates are keys whose
    row-0 bucket is selected); rows 1.. serve count-min estimates."""
    if width <= 0:
        raise ValueError("sketch width must be positive")
    h = np.asarray(hashes, dtype=np.uint64)
    out = np.empty((depth, len(h)), dtype=np.int32)
    with np.errstate(over="ignore"):
        for d in range(depth):
            salt = mix64(np.uint64(
                ((seed & _MASK64) ^ ((d + 1) * _GOLDEN)) & _MASK64))
            out[d] = (mix64(h ^ salt) % np.uint64(width)).astype(np.int32)
    return out


def build_candidate_table(uniq_keys: Sequence, selected_of_key: np.ndarray
                          ) -> Tuple[list, dict]:
    """The host-side key→candidate-id encoding table: the keys of
    ``uniq_keys`` (factorization order — ascending for NumPy-sortable
    dtypes) whose row-0 bucket was selected, paired with dense
    candidate ids in that order.

    NOT a DP release: the table is phase-2 *input* (it restricts which
    rows the exact dense pass sees); only phase 2's own private
    partition selection decides what is released. Construction is
    confined to ``sketch/`` by the ``sketch-confinement`` lint.
    """
    sel = np.asarray(selected_of_key, dtype=bool)
    if isinstance(uniq_keys, np.ndarray):
        cand = uniq_keys[sel].tolist()
    else:
        cand = [k for k, s in zip(uniq_keys, sel) if s]
    return cand, {k: i for i, k in enumerate(cand)}
