"""``SketchParams`` — the sketch-first entry point's DP knob set.

The fields split into two tiers:

* **DP parameters** — ``eps``/``delta`` (the phase-1 candidate
  selection's own budget, drawn through a dedicated
  ``NaiveBudgetAccountant`` and audited like every other mechanism),
  ``width``/``depth``/``candidate_cap``/``max_buckets_contributed``
  (they change which buckets are selected and therefore which keys the
  exact pass can release — the planner treats the corresponding knobs
  as dp-UNSAFE, same class as ``stream_chunk_rows``).
* **Execution choices** — ``backend`` (the one-hot-matmul binner vs
  the XLA scatter reference, bit-identical by construction: PARITY
  row 36) and ``chunk_rows`` (device batch sizing of the bounded-pair
  stream; the sketch is a sum, so chunking is associativity-exact).

Fields left ``None`` resolve through the planner registry
(``plan/knobs.py``: ``sketch_width`` / ``sketch_depth`` /
``sketch_candidate_cap`` / ``sketch_backend``, env > plan > default).
Like the serve knobs, the sketch knobs carry no module seam —
``SketchParams`` itself is the injection point — so resolving the
registry never imports this package into non-sketch runs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pipelinedp_tpu.sketch import hashing

#: The matmul binner factors buckets into (hi, lo) radix digits with a
#: 256-wide low digit; widths round up to this multiple on device.
WIDTH_MULTIPLE = 256


@dataclasses.dataclass(frozen=True)
class SketchParams:
    """Parameters of the two-phase sketch-first DP heavy-hitters path
    (``DPEngine.aggregate(..., sketch_first=SketchParams(...))``).

    ``eps``/``delta`` fund phase 1 only (bucket-level candidate
    selection); the engine's own accountant funds phase 2 exactly as a
    dense run — total privacy cost is the sum of the two, and both
    sides land in the audit record.
    """

    #: Phase-1 selection epsilon: the per-bucket noisy mass vector is
    #: released at Laplace scale ``max_buckets_contributed / eps``
    #: (L1 sensitivity of the bounded per-user contributions), so the
    #: selected-bucket set is ``eps``-DP before any thresholding.
    eps: float
    #: Funds the suppression threshold's tail calibration (the same
    #: Laplace-thresholding formula as dense partition selection).
    #: With the bucket axis public the threshold is post-processing of
    #: the eps-DP noisy vector — delta tightens utility, it is not
    #: load-bearing for privacy. May be 0 (threshold falls back to 1).
    delta: float
    #: Hash buckets per sketch row (row 0 is the selection axis).
    #: None → the ``sketch_width`` knob. Rounded up to a multiple of
    #: 256 on device (the matmul binner's radix width).
    width: Optional[int] = None
    #: Sketch rows (independent hash remixes). Row 0 selects; rows 1+
    #: refine the count-min mass estimate in the run report. None →
    #: the ``sketch_depth`` knob.
    depth: Optional[int] = None
    #: Max SELECTED buckets (DP top-K over noisy mass — the cap lives
    #: inside the DP mechanism, so a neighbor dataset can never slide
    #: un-selected keys into the candidate set). None → the
    #: ``sketch_candidate_cap`` knob.
    candidate_cap: Optional[int] = None
    #: Per-user bound on distinct keys entering the sketch (the L0 of
    #: phase 1, bounded BEFORE accumulation by a deterministic seeded
    #: per-user sample). None → the aggregation's
    #: ``max_partitions_contributed`` (or ``max_contributions``).
    max_buckets_contributed: Optional[int] = None
    #: Explicit suppression threshold on noisy bucket mass (post-
    #: processing). None → the Laplace-thresholding formula at
    #: (eps, delta, L0); with delta == 0, 1.0.
    threshold: Optional[float] = None
    #: Seed of the stable key hash (NOT the noise seed — noise keys
    #: derive from the backend ``rng_seed``).
    hash_seed: int = hashing.DEFAULT_SEED
    #: "matmul" (one-hot radix binner, MXU-shaped) or "xla" (scatter
    #: reference). Bit-identical; None → the ``sketch_backend`` knob.
    backend: Optional[str] = None
    #: Bounded (user, key) pairs per device batch of the sketch
    #: accumulation stream. Exact for any value (integer sum).
    chunk_rows: int = 1 << 20

    def __post_init__(self):
        if not self.eps > 0:
            raise ValueError("SketchParams.eps must be positive")
        if not 0 <= self.delta < 1:
            raise ValueError("SketchParams.delta must be in [0, 1)")
        for name in ("width", "depth", "candidate_cap",
                     "max_buckets_contributed"):
            v = getattr(self, name)
            if v is not None and (not isinstance(v, int) or v <= 0):
                raise ValueError(f"SketchParams.{name} must be a "
                                 f"positive int (got {v!r})")
        if self.backend is not None and self.backend not in ("matmul",
                                                             "xla"):
            raise ValueError("SketchParams.backend must be 'matmul' or "
                             f"'xla' (got {self.backend!r})")
        if self.chunk_rows <= 0:
            raise ValueError("SketchParams.chunk_rows must be positive")

    # --- knob resolution (explicit param > planner registry) ---

    def _knob(self, explicit, knob_name: str):
        if explicit is not None:
            return explicit
        from pipelinedp_tpu import plan as plan_mod
        return plan_mod.knob_value(knob_name)

    def resolved_width(self) -> int:
        w = int(self._knob(self.width, "sketch_width"))
        return -(-w // WIDTH_MULTIPLE) * WIDTH_MULTIPLE

    def resolved_depth(self) -> int:
        return int(self._knob(self.depth, "sketch_depth"))

    def resolved_candidate_cap(self) -> int:
        return int(self._knob(self.candidate_cap, "sketch_candidate_cap"))

    def resolved_backend(self) -> str:
        return str(self._knob(self.backend, "sketch_backend"))

    def resolved_l0(self, agg_params) -> int:
        if self.max_buckets_contributed is not None:
            return self.max_buckets_contributed
        l0 = (getattr(agg_params, "max_partitions_contributed", None)
              or getattr(agg_params, "max_contributions", None))
        if not l0:
            raise ValueError(
                "sketch-first needs a cross-partition bound: set "
                "SketchParams.max_buckets_contributed or the "
                "aggregation's max_partitions_contributed")
        return int(l0)
