"""Non-private peeker sketches — the legacy ``DataPeeker.sketch``
plumbing, now owned by the sketch subsystem.

These are **NOT DP releases**: the rows carry raw per-(partition,
user) aggregates over a partition sample, for interactive utility
preview only (the reference's ``utility_analysis/data_peeker.py``
shape, SURVEY.md §2.8 — "not a DP aggregation, don't release").
``peeker.DataPeeker`` is a thin shim over this module; the genuinely
DP sketch path is ``sketch/engine.py`` (two-phase heavy hitters),
which shares none of this code's outputs.
"""

from __future__ import annotations

import functools


def _extract_fn(data_extractors, row):
    return (data_extractors.privacy_id_extractor(row),
            data_extractors.partition_extractor(row),
            data_extractors.value_extractor(row))


def sample_partitions(backend, col, n_partitions):
    """(pk, value) -> same, keeping only ``n_partitions`` sampled
    partition keys (NON-private reservoir sample)."""
    col = backend.group_by_key(col, "Group by pk")
    col = backend.map_tuple(col, lambda pk, vs: (1, (pk, vs)),
                            "Rekey to (1, (pk, values))")
    col = backend.sample_fixed_per_key(col, n_partitions,
                                       "Sample partitions")
    return backend.flat_map(col, lambda one_and_list: one_and_list[1],
                            "Extract sampled (pk, values)")


def non_private_sketch(backend, input_data, params, data_extractors):
    """One row (partition_key, aggregated_value, partition_count) per
    unique (pk, privacy_id), over a sample of partitions — raw values,
    NOT releasable (reference ``data_peeker.py:77-183``)."""
    from pipelinedp_tpu.aggregate_params import Metrics
    from pipelinedp_tpu.peeker import non_private_combiners

    if params.metrics is None:
        raise ValueError("Must provide aggregation metrics for sketch.")
    if len(params.metrics) != 1 or params.metrics[0] not in (
            Metrics.SUM, Metrics.COUNT):
        raise ValueError("Sketch only supports a single aggregation "
                         "and it must be COUNT or SUM.")
    combiner = non_private_combiners.create_compound_combiner(
        params.metrics)

    col = backend.map(input_data,
                      functools.partial(_extract_fn, data_extractors),
                      "Extract (privacy_id, partition_key, value)")
    col = backend.map_tuple(col, lambda pid, pk, v: (pk, (pid, v)),
                            "Rekey to (pk, (pid, value))")
    col = sample_partitions(backend, col,
                            params.number_of_sampled_partitions)

    def flatten_sampled(pk_and_pid_values):
        pk, pid_values = pk_and_pid_values
        return [((pk, pid), v) for pid, v in pid_values]

    col = backend.flat_map(col, flatten_sampled,
                           "Flatten to ((pk, pid), value)")
    col = backend.group_by_key(col, "Group by (pk, pid)")
    col = backend.map_values(col, combiner.create_accumulator,
                             "Aggregate per (pk, pid)")
    # ((pk, pid), compound_accumulator)
    col = backend.map_tuple(
        col, lambda pk_pid, acc: (pk_pid[1], (pk_pid[0], acc)),
        "Rekey to (pid, (pk, accumulator))")
    col = backend.group_by_key(col, "Group by privacy id")

    def attach_partition_count(pk_acc_list):
        partition_count = len(set(pk for pk, _ in pk_acc_list))
        return partition_count, pk_acc_list

    col = backend.map_values(col, attach_partition_count,
                             "Compute partition count")

    def flatten_results(pid_and_rest):
        _, (pcount, pk_acc_list) = pid_and_rest
        # Compound accumulator = (row_count, (child_acc,)); the single
        # raw child accumulator IS the aggregated value.
        return [(pk, acc[1][0], pcount) for pk, acc in pk_acc_list]

    return backend.flat_map(
        col, flatten_results,
        "Flatten to (pk, aggregated_value, partition_count)")
