#!/usr/bin/env python
"""Benchmark: fused TPU plane vs the reference-architecture LocalBackend.

Workload = BASELINE.md config (MovieLens-shaped): COUNT+SUM+MEAN over 60k
partitions with private partition selection. The baseline is this repo's
``LocalBackend`` — architecturally identical to the reference's
(``pipeline_dp/pipeline_backend.py:458``: lazy pure-Python generators), and
the reference publishes no numbers of its own (BASELINE.md). Throughput is
measured as input rows/second end-to-end (encode + bound + combine +
select + noise), fused timing excludes compilation (first run warms the
cache).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import argparse
import json
import sys
import time

import numpy as np


def make_dataset(n_rows, n_users, n_partitions, seed=0):
    rng = np.random.default_rng(seed)
    import pipelinedp_tpu as pdp
    # Zipf-ish partition popularity, like movie views.
    raw = rng.zipf(1.3, size=n_rows) % n_partitions
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n_rows),
        partition_keys=raw.astype(np.int64),
        values=rng.uniform(0.0, 10.0, n_rows))


def build_params():
    import pipelinedp_tpu as pdp
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4,
        max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)


def run_once(backend, dataset, eps=1.0, delta=1e-6):
    import pipelinedp_tpu as pdp
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, backend)
    result = engine.aggregate(dataset, build_params(),
                              pdp.DataExtractors())
    acc.compute_budgets()
    t0 = time.perf_counter()
    out = list(result)
    dt = time.perf_counter() - t0
    return len(out), dt


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes for a quick correctness pass")
    parser.add_argument("--rows", type=int, default=None)
    args = parser.parse_args()

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.backends import JaxBackend

    if args.smoke:
        n_rows, n_users, n_parts, local_rows = 50_000, 5_000, 2_000, 20_000
    else:
        n_rows = args.rows or 5_000_000
        n_users, n_parts, local_rows = 200_000, 60_000, 250_000

    # Same distribution for both planes: the local baseline runs a prefix
    # slice of the identical dataset, so per-row cost is comparable.
    fused_ds = make_dataset(n_rows, n_users, n_parts)
    local_ds = pdp.ArrayDataset(
        privacy_ids=fused_ds.privacy_ids[:local_rows],
        partition_keys=fused_ds.partition_keys[:local_rows],
        values=fused_ds.values[:local_rows])

    # Baseline: reference-architecture LocalBackend.
    n_local, local_dt = run_once(pdp.LocalBackend(), local_ds)
    local_rps = local_rows / local_dt

    # Fused plane: warm-up run compiles; measured run reuses the cache.
    backend = JaxBackend(rng_seed=0)
    run_once(backend, fused_ds)
    n_fused, fused_dt = run_once(backend, fused_ds)
    fused_rps = n_rows / fused_dt

    print(json.dumps({
        "metric": "dp_count_sum_mean_rows_per_sec",
        "value": round(fused_rps),
        "unit": "rows/s",
        "vs_baseline": round(fused_rps / local_rps, 2),
    }))
    print(f"# local: {local_rows} rows -> {n_local} partitions in "
          f"{local_dt:.2f}s ({local_rps:.0f} rows/s)", file=sys.stderr)
    print(f"# fused: {n_rows} rows -> {n_fused} partitions in "
          f"{fused_dt:.2f}s ({fused_rps:.0f} rows/s)", file=sys.stderr)


if __name__ == "__main__":
    main()
