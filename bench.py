#!/usr/bin/env python
"""Benchmark: fused TPU plane vs the reference-architecture LocalBackend.

Covers the five BASELINE.md measurement configs:

  1. COUNT over ~1k partitions            (movie_view_ratings, small keyspace)
  2. COUNT+SUM+MEAN over 60k partitions   (flagship; the r01 headline config)
  2b. SUM+MEAN, Gaussian mechanism, 60k partitions
  3. PRIVACY_ID_COUNT with Laplace-thresholding partition selection
     (restaurant_visits-shaped)
  4. PERCENTILE(50/90/99)+VARIANCE over 10M rows / 100k partitions
  5. utility-analysis epsilon-sweep, many configurations at once

The baseline is this repo's ``LocalBackend`` — architecturally identical
to the reference's (``pipeline_dp/pipeline_backend.py:458``: lazy
pure-Python generators); the reference publishes no numbers of its own
(BASELINE.md). The local baseline runs a prefix slice of the identical
dataset and is scaled to rows/sec; ``vs_baseline`` = fused rows/sec over
local rows/sec on the same workload.

Prints ONE JSON line on stdout (the flagship config), including the
host/device timing split — under ``--compare`` a one-line ``COMPARE:``
verdict precedes it (the JSON headline stays the LAST stdout line).
Per-config JSON lines go to stderr, prefixed with nothing — each is
itself valid JSON preceded by "##" comment lines for humans.

With ``PIPELINEDP_TPU_HEARTBEAT`` set, a monitor thread additionally
streams an atomically-replaced heartbeat file (progress, rows/s,
pace-vs-baseline) and watches for stalls: a wedged device probe is
cancelled at the stall deadline (``PIPELINEDP_TPU_STALL_S``) instead of
the full 300s probe timeout, and the degraded artifact embeds the
flight-record path and stall diagnosis.

Every record (and the final run report) also appends to the durable
run-ledger store (``obs.store``; ``PIPELINEDP_TPU_LEDGER_DIR``, else a
compile-cache sibling, else ``./.pdp_ledger``). ``--compare`` diffs the
run against the store's last-known-good entries for the same
environment fingerprint — degraded captures are never baselines — and
``--strict`` turns a >10% rate drop into a nonzero exit.
"""

import argparse
import json
import os
import sys

import numpy as np


def log(s):
    print(s, file=sys.stderr, flush=True)


_TRACER = None


def tracer():
    """The bench's span tracer (lazy: constructed after the platform/
    degradation env is settled). Always measures — every timing number
    in a bench record is a span duration — and records full spans into
    the obs ledger when PIPELINEDP_TPU_TRACE is set."""
    global _TRACER
    if _TRACER is None:
        from pipelinedp_tpu import obs
        _TRACER = obs.run_tracer()
    return _TRACER


_ENV_FP = None


def env_fingerprint():
    """Environment fingerprint attached to EVERY bench record (traced
    or not): jax/jaxlib versions, device kind/count, git SHA, active
    PIPELINEDP_TPU_* flags, degraded flag — so a BENCH_r*.json is
    attributable without session notes. Cached: one probe per run."""
    global _ENV_FP
    if _ENV_FP is None:
        from pipelinedp_tpu import obs
        _ENV_FP = obs.environment_fingerprint()
    return _ENV_FP


class _BenchLedger:
    """The bench's connection to the durable run-ledger store
    (``obs.store``): every emitted record appends one fsync'd entry, and
    ``--compare`` reads baselines from a snapshot taken BEFORE this
    run's first append — a run never compares against itself. An
    unavailable store (unwritable dir) degrades to a logged no-op; the
    bench must never die to its own bookkeeping."""

    def __init__(self):
        import uuid

        from pipelinedp_tpu.obs import store as obs_store
        self._store = None
        self.fingerprint = None
        self.run_id = uuid.uuid4().hex[:12]
        self._baseline_entries = []
        self._failed_runs = set()
        try:
            directory = obs_store.ledger_dir(
                default=os.path.join(os.getcwd(), ".pdp_ledger"))
            self._store = obs_store.LedgerStore(directory)
            self.fingerprint = obs_store.fingerprint_key(env_fingerprint())
            self._baseline_entries = self._store.entries()
            # Runs that FAILED a --strict gate marked themselves
            # (bench.gate_failed): their regressed numbers must not
            # become the next run's baseline, or the gate would fire
            # once and then self-clear without any fix.
            self._failed_runs = {
                e.get("run_id") for e in self._baseline_entries
                if e.get("name") == "bench.gate_failed" and
                e.get("run_id") is not None}
            log(f"## run ledger: {self._store.path} "
                f"({len(self._baseline_entries)} prior entries, "
                f"fingerprint {self.fingerprint})")
        except OSError as e:
            log(f"## run-ledger store unavailable ({e}); records will "
                "not persist")
            self._store = None

    def append(self, name, payload):
        if self._store is None:
            return
        try:
            self._store.append(name, payload, env=env_fingerprint(),
                               run_id=self.run_id)
        except OSError as e:
            log(f"## run-ledger append failed for {name}: {e}")

    @staticmethod
    def _entry_value(entry):
        v = ((entry.get("payload") or {}).get("record") or {}).get("value")
        return v if isinstance(v, (int, float)) else None

    def baseline(self, name):
        """(baseline pre-run entry or None, skipped_degraded) for this
        run's fingerprint. The baseline is the BEST sample of ``name``
        from the most recent eligible run — the same best-of rule the
        headline applies within a run, so a slow-window re-sample never
        becomes the bar. Ineligible: ``degraded: true`` entries (the
        tunnel-wedged capture) and entries from runs that failed a
        --strict gate. ``skipped_degraded`` is True when a NEWER
        degraded entry was passed over."""
        if self._store is None:
            return None, False
        pool = [e for e in self._baseline_entries
                if e.get("name") == name and
                e.get("fingerprint") == self.fingerprint]
        if not pool:
            return None, False
        eligible_i = [i for i, e in enumerate(pool)
                      if not e.get("degraded") and
                      e.get("run_id") not in self._failed_runs]
        if not eligible_i:
            return None, any(e.get("degraded") for e in pool)
        eligible = [pool[i] for i in eligible_i]
        last = eligible[-1]
        best = last
        for e in eligible:
            if e.get("run_id") != last.get("run_id"):
                continue  # best WITHIN the most recent eligible run
            v, b = self._entry_value(e), self._entry_value(best)
            if v is not None and (b is None or v > b):
                best = e
        # ANY newer degraded capture was passed over — not just when it
        # happens to be the single newest entry (a gate-failed run in
        # between must not mask the skip notification).
        skipped = any(e.get("degraded")
                      for e in pool[eligible_i[-1] + 1:])
        return best, skipped


_BENCH_LEDGER = None
_RUN_RECORDS = []
_PLAN_PROV = None


def _bench_ledger():
    global _BENCH_LEDGER
    if _BENCH_LEDGER is None:
        _BENCH_LEDGER = _BenchLedger()
    return _BENCH_LEDGER


def reset_run_state():
    """Fresh bench 'run' within one process (tests simulating two
    driver invocations): clears the cached tracer / fingerprint /
    ledger connection / record list and the obs process ledger."""
    global _TRACER, _ENV_FP, _BENCH_LEDGER, _RUN_RECORDS, _PLAN_PROV
    _TRACER = None
    _ENV_FP = None
    _BENCH_LEDGER = None
    _RUN_RECORDS = []
    _PLAN_PROV = None
    from pipelinedp_tpu import obs
    obs.reset()


def plan_provenance():
    """{plan_source, plan_hash} stamped on every bench record:
    ``autotuned`` when a plan file steered any knob, ``env-override``
    when an env var or test seam did, ``default`` otherwise — the
    fields ``--compare`` uses to refuse gating an autotuned run
    against a default-knob baseline (and vice versa).

    Snapshotted ONCE per bench run, at the first call (main() takes it
    right after the plan dir resolves, before any record runs): later
    records run under bench-internal measurement scaffolding — the
    streamed record's chunk env, the capped probe records' seam
    injections — and labeling those as ``env-override`` would misstate
    the regime every plain run was launched under."""
    global _PLAN_PROV
    if _PLAN_PROV is None:
        from pipelinedp_tpu import plan as plan_mod
        try:
            _PLAN_PROV = plan_mod.source_summary()
        except Exception:
            _PLAN_PROV = {"plan_source": "default", "plan_hash": None}
    return dict(_PLAN_PROV)


def kernel_backend_in_force():
    """The resolved ``kernel_backend`` knob (env > seam > plan >
    default), stamped on every bench record so ``--compare`` can
    refuse to gate an ``xla`` rate against a ``pallas`` baseline (two
    different device programs — a delta there is a backend
    difference, not a regression)."""
    try:
        from pipelinedp_tpu import plan as plan_mod
        return str(plan_mod.knob_value("kernel_backend"))
    except Exception:
        return "xla"


def mesh_topology_in_force():
    """The resolved ``mesh_topology`` knob (env > seam > plan >
    default), stamped on every bench record so ``--compare`` can
    refuse to gate a flat-exchange rate against a hierarchical
    baseline (two different collective schedules — released values
    are bit-identical by PARITY row 43, but the rate delta is a
    topology difference, not a regression)."""
    try:
        from pipelinedp_tpu.parallel import sharded as psh
        return psh.resolved_topology_mode()
    except Exception:
        return "flat"


def emit(rec):
    """Log one record (with the env fingerprint, the plan provenance
    and the kernel backend merged) as JSON, and append it to the
    durable run-ledger store keyed by the environment fingerprint."""
    rec["env"] = env_fingerprint()
    rec.update(plan_provenance())
    rec.setdefault("kernel_backend", kernel_backend_in_force())
    rec.setdefault("mesh_topology", mesh_topology_in_force())
    log(json.dumps(rec))
    _RUN_RECORDS.append(rec)
    _bench_ledger().append(rec["metric"], {"record": rec})


def zipf_dataset(n_rows, n_users, n_partitions, seed=0, value_hi=10.0):
    import pipelinedp_tpu as pdp
    rng = np.random.default_rng(seed)
    # Zipf-ish partition popularity, like movie views; the modulo keeps
    # every partition reachable so ~all n_partitions are populated.
    raw = rng.zipf(1.3, size=n_rows) % n_partitions
    return pdp.ArrayDataset(
        privacy_ids=rng.integers(0, n_users, n_rows),
        partition_keys=raw.astype(np.int64),
        values=rng.uniform(0.0, value_hi, n_rows))


def slice_dataset(ds, n):
    import pipelinedp_tpu as pdp
    return pdp.ArrayDataset(privacy_ids=ds.privacy_ids[:n],
                            partition_keys=ds.partition_keys[:n],
                            values=ds.values[:n])


def run_once(backend, dataset, params, eps=1.0, delta=1e-6):
    """Returns (n_output_partitions, seconds, timings|None)."""
    import pipelinedp_tpu as pdp
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=delta)
    engine = pdp.DPEngine(acc, backend)
    result = engine.aggregate(dataset, params, pdp.DataExtractors())
    acc.compute_budgets()
    with tracer().span("bench.aggregate", cat="bench",
                       backend=type(backend).__name__) as sp:
        out = list(result)
    return len(out), sp.duration, getattr(result, "timings", None)


def bench_config(name, params, fused_ds, local_rows, repeats=5,
                 local_baseline=None):
    """One BASELINE config: local scaling-curve baseline + best-of-N
    fused run. Best-of-5 because the tunneled host link's throughput
    swings ~4x between quiet and busy windows; the best run reflects the
    pipeline, not the link's worst moment.

    The LocalBackend baseline is measured at THREE sizes (n/4, n/2, n of
    ``local_rows``) so the rate-vs-size trend is recorded alongside the
    rate: comparing a small-prefix local rate against the full-size
    fused run assumes rate-linearity, and the curve shows the direction
    of that assumption's error. LocalBackend's per-partition Python dict
    churn makes its rate fall (or at best stay flat) with size, so a
    falling curve means the reported vs_baseline is a LOWER bound."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.backends import JaxBackend

    # Same best-of-N on both sides of the ratio: each side reports its
    # quietest window (host load for local, link load for fused), so the
    # sampling quantile is symmetric and neither gets a luckier draw.
    if local_baseline is not None:
        # Re-sample runs guard only the fused/tunneled side; reuse the
        # first sample's (CPU-side) local baseline.
        local_scaling, local_dt = local_baseline
        n_local = None
        local_rps = local_rows / local_dt
    else:
        local_scaling = []
        for nl in (max(local_rows // 4, 1000),
                   max(local_rows // 2, 1000), local_rows):
            ds_l = slice_dataset(fused_ds, nl)
            n_local, dt_l, _ = min(
                (run_once(pdp.LocalBackend(), ds_l, params)
                 for _ in range(repeats)), key=lambda r: r[1])
            local_scaling.append((nl, round(nl / dt_l)))
        local_dt = dt_l  # measured at the largest size, last iteration
        local_rps = local_rows / local_dt

    backend = JaxBackend(rng_seed=0)
    # First run pays compilation + the host->device transfer of the
    # dataset; it also populates the dataset's device cache, so the
    # timed repeats measure aggregation over device-resident columns —
    # the recurring cost of the multi-aggregation workloads (tuning,
    # multi-metric pipelines) this plane exists for. A second cold run
    # (fresh ArrayDataset, warm compile cache) captures the one-time
    # ingest cost: host encode + link transfer + kernel + release.
    run_once(backend, fused_ds, params)  # compile warm-up
    cold_ds = slice_dataset(fused_ds, len(fused_ds))
    _, cold_dt, _ = run_once(backend, cold_ds, params)
    del cold_ds
    best = None
    for _ in range(repeats):
        n_fused, dt, timings = run_once(backend, fused_ds, params)
        if best is None or dt < best[1]:
            best = (n_fused, dt, timings)
    n_fused, fused_dt, timings = best
    n_rows = len(fused_ds)
    fused_rps = n_rows / fused_dt
    populated = len(np.unique(fused_ds.partition_keys))
    trend = local_scaling[-1][1] / max(local_scaling[0][1], 1)
    rec = {
        "metric": name,
        "value": round(fused_rps),
        "unit": "rows/s",
        "vs_baseline": round(fused_rps / local_rps, 2),
        "vs_baseline_cold": round((n_rows / cold_dt) / local_rps, 2),
        "rows": n_rows,
        "partitions_populated": populated,
        "partitions_kept": n_fused,
        "fused_s": round(fused_dt, 3),
        "cold_s": round(cold_dt, 3),
        "local_rows_per_s": round(local_rps),
        # [(rows, rows/s)] at n/4, n/2, n — the extrapolation evidence;
        # trend <= ~1 (rate flat or falling with size) means the
        # full-size local rate is no better than measured, so
        # vs_baseline is a lower bound.
        "local_scaling": local_scaling,
        "local_rate_trend": round(trend, 3),
    }
    if timings:
        rec["host_s"] = round(
            timings["host_encode_s"] + timings["host_decode_s"], 3)
        rec["device_s"] = round(timings["device_s"], 3)
    local_txt = (f"local {local_rows} rows -> {n_local} parts in "
                 f"{local_dt:.2f}s ({local_rps:.0f} rows/s)"
                 if n_local is not None else
                 f"local baseline reused ({local_rps:.0f} rows/s)")
    log(f"## {name}: {local_txt}; fused {n_rows} rows -> "
        f"{n_fused} parts in {fused_dt:.2f}s ({fused_rps:.0f} rows/s)")
    emit(rec)
    rec["_local_baseline"] = (local_scaling, local_dt)  # for re-samples
    return rec


def bench_analysis_sweep(n_rows, n_users, n_partitions, n_configs):
    """BASELINE config 5: the epsilon/clip-sweep utility analysis. Measures
    configurations x rows per second, fused vs the host analysis path."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    from pipelinedp_tpu.backends import JaxBackend

    ds = zipf_dataset(n_rows, n_users, n_partitions, seed=1)

    def sweep_options(n_cfg):
        if n_cfg >= 1000:
            # BASELINE config 5 at spec: a 10k-configuration grid over
            # the contribution caps (l0 x linf), all distinct.
            side = int(round(np.sqrt(n_cfg)))
            l0s = range(1, side + 1)
            linfs = range(1, n_cfg // side + 1)
            pairs = [(a, b) for a in l0s for b in linfs]
            multi = analysis.MultiParameterConfiguration(
                max_partitions_contributed=[p[0] for p in pairs],
                max_contributions_per_partition=[p[1] for p in pairs])
            n_eff = len(pairs)
        else:
            caps = np.unique(np.geomspace(1, 60, n_cfg).astype(int))
            multi = analysis.MultiParameterConfiguration(
                max_partitions_contributed=caps.tolist(),
                max_contributions_per_partition=[2] * len(caps))
            n_eff = len(caps)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4, max_contributions_per_partition=2)
        return n_eff, analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=params,
            multi_param_configuration=multi)

    extractors = pdp.DataExtractors()

    def run(backend, data, options):
        with tracer().span("bench.sweep_run", cat="bench",
                           backend=type(backend).__name__) as sp:
            res = analysis.perform_utility_analysis(data, backend,
                                                    options, extractors)
            n = len(list(res))
        return n, sp.duration

    # The pure-Python baseline is far too slow for the full sweep: measure
    # its unit rate (configs x rows per second) on a small slice and scale.
    # Best-of-3 (the per-config baselines use best-of-5; the sweep's
    # host leg is slower per run): a single host measurement swings with
    # CPU load and distorts the ratio.
    base_rows = min(n_rows, 20_000)
    base_cfg, base_options = sweep_options(min(n_configs, 8))
    host_dt = min(
        run(pdp.LocalBackend(), slice_dataset(ds, base_rows),
            base_options)[1] for _ in range(3))
    host_unit_rate = base_cfg * base_rows / host_dt

    n_eff, options = sweep_options(n_configs)
    jax_backend = JaxBackend(rng_seed=0)
    run(jax_backend, ds, options)  # warm-up
    n_fused, fused_dt = run(jax_backend, ds, options)
    unit_per_s = n_eff * n_rows / fused_dt

    # Host-oracle spot check: a sampled config subset on a small slice
    # must agree between the device sweep and the pure-Python graph.
    spot_cfg, spot_options = sweep_options(3)
    spot_ds = slice_dataset(ds, base_rows)
    host_res = list(analysis.perform_utility_analysis(
        spot_ds, pdp.LocalBackend(), spot_options, extractors))[0]
    fused_res = list(analysis.perform_utility_analysis(
        spot_ds, jax_backend, spot_options, extractors))[0]
    oracle_ok = len(host_res) == len(fused_res) == spot_cfg
    for h, f in zip(host_res, fused_res):
        hv = h.count_metrics.error_expected
        fv = f.count_metrics.error_expected
        if abs(hv - fv) > max(0.05 * abs(hv), 0.5):
            oracle_ok = False
            log(f"## SWEEP ORACLE MISMATCH: host {hv} fused {fv}")
    rec = {
        "metric": "analysis_sweep_config_rows_per_sec",
        "value": round(unit_per_s),
        "unit": "config*rows/s",
        "vs_baseline": round(unit_per_s / host_unit_rate, 2),
        "rows": n_rows,
        "configs": n_eff,
        "fused_s": round(fused_dt, 3),
        "local_unit_rate": round(host_unit_rate),
        "oracle_check": "ok" if oracle_ok else "MISMATCH",
    }
    log(f"## analysis sweep: {n_eff} configs x {n_rows} rows in "
        f"{fused_dt:.2f}s; host baseline {host_unit_rate:.0f} config*rows/s "
        f"(measured on {base_cfg} cfg x {base_rows} rows)")
    emit(rec)
    return rec


def bench_utility_megasweep(n_rows, smoke=False):
    """The utility-analysis megasweep record: configurations as a device
    axis. For each K in {16, 64, 256} (smoke: {4, 16}) the SAME K-config
    (l0 x linf) grid over one >=1e6-row synthetic runs twice in one
    process — walked (``sweep_config_batch=1``: one dispatch per config,
    the host-walk baseline) vs batched (width K: every config rides one
    dispatch of one warm executable whose bounds/eps-splits/selection
    tables/noise kinds are runtime inputs) — with the outputs
    cross-checked bit-for-bit per config. The cost observatory is
    force-enabled for the record's duration, so the dispatch-count
    collapse is WITNESSED, not asserted: the sweep-chunk program's
    ``calls`` delta across each timed leg is ceil(K/width), and the
    batched timed leg captures zero new programs (the executable was
    warm). The record carries configs/s, configs*partitions/s, the
    sweep phase's roofline verdict and the ``sweep_config_batch``
    stamp ``--compare`` refuses to gate across."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import analysis
    from pipelinedp_tpu import plan as plan_mod
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.obs import costs as obs_costs

    parts = 200 if smoke else 2_000
    ds = zipf_dataset(n_rows, max(1_000, n_rows // 25), parts, seed=23)
    extractors = pdp.DataExtractors()
    backend = JaxBackend(rng_seed=0)
    _SWEEP_PROGRAMS = ("_sweep_chunk_body", "_sweep_chunk_sharded")

    def grid_options(k):
        # K distinct (l0, linf) pairs — the BASELINE config-5 grid shape
        # at width K, so every config is a genuinely different
        # contribution-bounding hypothesis.
        side = int(round(np.sqrt(k)))
        pairs = [(a, b) for a in range(1, side + 1)
                 for b in range(1, k // side + 1)]
        multi = analysis.MultiParameterConfiguration(
            max_partitions_contributed=[p[0] for p in pairs],
            max_contributions_per_partition=[p[1] for p in pairs])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2)
        return len(pairs), analysis.UtilityAnalysisOptions(
            epsilon=1.0, delta=1e-6, aggregate_params=params,
            multi_param_configuration=multi)

    def sweep_calls():
        snap = obs_costs.TABLE.snapshot()
        return sum(e.get("calls", 0) for e in snap["programs"].values()
                   if e.get("program") in _SWEEP_PROGRAMS)

    def sweep_programs():
        snap = obs_costs.TABLE.snapshot()
        return sum(1 for e in snap["programs"].values()
                   if e.get("program") in _SWEEP_PROGRAMS)

    def run(options, width, label):
        with plan_mod.seam_override("sweep_config_batch", width):
            with tracer().span("bench.megasweep_run", cat="bench",
                               width=width, leg=label) as sp:
                res = list(analysis.perform_utility_analysis(
                    ds, backend, options, extractors))[0]
        return res, sp.duration

    prev_costs = os.environ.get(obs_costs.ENV_VAR)
    os.environ[obs_costs.ENV_VAR] = "1"
    # The cost table is process-global: preserve every program the
    # earlier records captured, exactly like the kernel-backend A/B.
    captured_programs = dict(obs_costs.TABLE.snapshot()["programs"])
    recs = []
    try:
        for k in ((4, 16) if smoke else (16, 64, 256)):
            n_cfg, options = grid_options(k)
            # Batched leg: width = K -> the whole grid is ONE dispatch.
            run(options, n_cfg, "batched_warm")     # compile + capture
            calls0, progs0 = sweep_calls(), sweep_programs()
            batched, batched_dt = run(options, n_cfg, "batched")
            calls1, progs1 = sweep_calls(), sweep_programs()
            batched_dispatches = calls1 - calls0
            new_programs_warm_leg = progs1 - progs0
            # Walked leg: width = 1 -> one dispatch per config (the
            # pre-megasweep host walk, measured in the same process on
            # the same data).
            run(options, 1, "walked_warm")
            calls2 = sweep_calls()
            walked, walked_dt = run(options, 1, "walked")
            walked_dispatches = sweep_calls() - calls2
            parity = len(batched) == len(walked) == n_cfg
            for b, w in zip(batched, walked):
                bm, wm = b.count_metrics, w.count_metrics
                for f in ("error_expected", "error_variance",
                          "error_l0_expected", "error_quantiles",
                          "ratio_data_dropped_l0"):
                    if getattr(bm, f) != getattr(wm, f):
                        parity = False
            if not parity:
                log(f"## MEGASWEEP PARITY MISMATCH at K={n_cfg} "
                    "(batched vs walked)")
            snap = obs_costs.TABLE.snapshot()
            sweep_phase = (snap["phases"] or {}).get("sweep") or {}
            captured_programs.update(snap["programs"])
            rec = {
                "metric": "utility_megasweep_configs_per_sec",
                "value": round(n_cfg / batched_dt, 1),
                "unit": "configs/s",
                "rows": n_rows,
                "partitions": parts,
                "configs": n_cfg,
                "sweep_config_batch": n_cfg,
                "batched_s": round(batched_dt, 3),
                "walked_s": round(walked_dt, 3),
                "walked_configs_per_s": round(n_cfg / walked_dt, 1),
                "configs_partitions_per_sec": round(
                    n_cfg * parts / batched_dt),
                "batched_vs_walked": round(walked_dt / batched_dt, 2),
                "dispatches_batched": batched_dispatches,
                "dispatches_walked": walked_dispatches,
                "new_programs_in_timed_leg": new_programs_warm_leg,
                "dispatch_check": (
                    "ok" if (batched_dispatches == 1
                             and walked_dispatches == n_cfg
                             and new_programs_warm_leg == 0)
                    else "MISMATCH"),
                "parity": "ok" if parity else "MISMATCH",
                "sweep_phase": {
                    "verdict": sweep_phase.get("verdict"),
                    "intensity": sweep_phase.get("intensity"),
                    "calls": sweep_phase.get("calls"),
                },
            }
            log(f"## megasweep K={n_cfg}: batched {batched_dt:.2f}s "
                f"({rec['value']} cfg/s, {batched_dispatches} dispatch) "
                f"vs walked {walked_dt:.2f}s "
                f"({rec['walked_configs_per_s']} cfg/s, "
                f"{walked_dispatches} dispatches) -> "
                f"{rec['batched_vs_walked']}x; parity {rec['parity']}; "
                f"dispatch check {rec['dispatch_check']}")
            emit(rec)
            recs.append(rec)
    finally:
        if prev_costs is None:
            os.environ.pop(obs_costs.ENV_VAR, None)
        else:
            os.environ[obs_costs.ENV_VAR] = prev_costs
        # Restore the run-wide table (earlier records' programs + this
        # record's captures) for the final run report.
        obs_costs.TABLE.reset()
        for key, entry in captured_programs.items():
            obs_costs.TABLE.record(key, entry)
    return recs


def bench_streaming(n_rows):
    """Streaming ingest past the single-batch capacity (VERDICT r3 #1):
    one COUNT+SUM+MEAN aggregation over ``n_rows`` rows — more than the
    2^27-row single-batch lane cap — through the chunked streaming path
    (``pipelinedp_tpu/streaming.py``). Streaming is single-shot by
    nature (every run re-ships the data), so the whole wall time counts;
    the dominant cost on this harness is the tunneled host link
    (~15 MB/s), which a real TPU host's PCIe would beat by ~100x.

    ``vs_baseline`` is apples-to-apples with the other configs: the
    LocalBackend rate is measured on a PREFIX of this same streaming
    dataset (same pid cardinality, same partition skew), best-of-3.
    LocalBackend's rate falls (or stays flat) with size, so the prefix
    rate is an upper bound on the full-size local rate and the reported
    ratio is a lower bound."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.backends import JaxBackend

    rng = np.random.default_rng(9)
    # int32/float32 columns: 150M rows cost ~1.8 GB host RAM and ship
    # as 3-byte pid planes + 2-byte pks + 4-byte values.
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 1 << 24, n_rows).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n_rows) % 50_000).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n_rows).astype(np.float32))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    # Local baseline on a prefix of the SAME dataset (same shape/skew).
    prefix = min(n_rows, 1_000_000)
    _, local_dt, _ = min((run_once(pdp.LocalBackend(),
                                   slice_dataset(ds, prefix), params)
                          for _ in range(3)), key=lambda r: r[1])
    local_rps = prefix / local_dt
    # Small (smoke) row counts still must exercise the streaming path:
    # force a chunk size below the dataset.
    import os
    from pipelinedp_tpu import streaming as streaming_mod
    did_set = False
    prev = os.environ.get(streaming_mod._CHUNK_ENV)
    if n_rows <= streaming_mod.stream_chunk_rows():
        os.environ[streaming_mod._CHUNK_ENV] = str(max(n_rows // 4, 1000))
        did_set = True
    try:
        with tracer().span("bench.streaming_run", cat="bench") as sp:
            n_parts, dt, timings = run_once(JaxBackend(rng_seed=0), ds,
                                            params)
        total = sp.duration
    finally:
        if did_set:
            if prev is None:
                os.environ.pop(streaming_mod._CHUNK_ENV, None)
            else:
                os.environ[streaming_mod._CHUNK_ENV] = prev
    rps = n_rows / total
    rec = {
        "metric": "dp_streaming_ingest_rows_per_sec",
        "value": round(rps),
        "unit": "rows/s",
        "vs_baseline": round(rps / local_rps, 2),
        "rows": n_rows,
        "partitions_kept": n_parts,
        "total_s": round(total, 3),
        "local_rows_per_s": round(local_rps),
        "local_prefix_rows": prefix,
        "stream_batches": (timings or {}).get("stream_batches"),
        "device_s": round((timings or {}).get("device_s", 0.0), 3),
        # Transfer/compute split: host staging+enqueue wall vs time
        # blocked on kernel results — near-zero fold_wait means the
        # link (not the TPU) is the bottleneck and the overlap works.
        "stage_s": round((timings or {}).get("stream_stage_s", 0.0), 3),
        "fold_wait_s": round(
            (timings or {}).get("stream_fold_wait_s", 0.0), 3),
        # Per-phase pass-A breakdown from the overlapped ingest
        # executor: busy seconds per phase vs the loop wall clock.
        # overlap works <=> t_total < t_stage + t_fold + t_device
        # (overlap_frac = the hidden fraction of phase time).
        "t_stage": round((timings or {}).get("stream_t_stage", 0.0), 3),
        "t_fold": round((timings or {}).get("stream_t_fold", 0.0), 3),
        "t_device": round(
            (timings or {}).get("stream_t_device", 0.0), 3),
        "t_total": round((timings or {}).get("stream_t_total", 0.0), 3),
        "overlap_frac": round(
            (timings or {}).get("stream_overlap_frac", 0.0), 3),
        "executor": (timings or {}).get("stream_executor"),
        # Elastic recovery provenance: 0 on a healthy run; nonzero
        # means the mesh shrank mid-stream and this throughput number
        # covers a re-form + checkpoint resume, not a clean pass.
        "mesh_reshards": (timings or {}).get("stream_mesh_reshards", 0),
    }
    log(f"## streaming ingest: {n_rows} rows ({rec['stream_batches']} "
        f"batches) in {total:.1f}s ({rps:.0f} rows/s, cold incl. "
        f"compile + host link); pass-A overlap {rec['overlap_frac']:.0%} "
        f"(stage {rec['t_stage']} + fold {rec['t_fold']} + device "
        f"{rec['t_device']} vs wall {rec['t_total']}, {rec['executor']})")
    emit(rec)
    return rec


def bench_streamed_percentile(n_rows):
    """Streamed two-pass percentiles: the pass-B sweep planner's
    driver-witnessed evidence. Emits TWO records:

    * ``dp_streamed_percentile_rows_per_sec`` — the default-cap run,
      with the pass-B source (device_cache / hybrid / reship), sweep
      count and reshipped bytes in the record;
    * ``pass_b_sweep`` — the same workload under a shrunken
      ``je._SUBHIST_BYTE_CAP`` seam that forces the multi-tile sweep
      path (>= 4 tiles), so a CPU bench run witnesses the round-count
      collapse (``pass_b_sweeps`` < ``pass_b_tiles``) and the
      bit-parity against the default-cap run — not just the one-tile
      fast case."""
    import os

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import jax_engine as je
    from pipelinedp_tpu import streaming as streaming_mod
    from pipelinedp_tpu.backends import JaxBackend

    rng = np.random.default_rng(13)
    parts = 3_000
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 1 << 20, n_rows).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n_rows).astype(np.float32))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                 pdp.Metrics.PERCENTILE(99), pdp.Metrics.VARIANCE],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    prev = os.environ.get(streaming_mod._CHUNK_ENV)
    did_set = False
    if n_rows <= streaming_mod.stream_chunk_rows():
        os.environ[streaming_mod._CHUNK_ENV] = str(max(n_rows // 6,
                                                       1000))
        did_set = True

    def run(label):
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        result = engine.aggregate(ds, params, pdp.DataExtractors(),
                                  public_partitions=list(range(parts)))
        acc.compute_budgets()
        with tracer().span(f"bench.pct_stream.{label}",
                           cat="bench") as sp:
            out = dict(result)
        return out, sp.duration, result.timings or {}

    try:
        out, dt, timings = run("default")
        rec = {
            "metric": "dp_streamed_percentile_rows_per_sec",
            "value": round(n_rows / dt),
            "unit": "rows/s",
            "rows": n_rows,
            "partitions": parts,
            "total_s": round(dt, 3),
            "stream_batches": timings.get("stream_batches"),
            "pass_b_source": timings.get("stream_pass_b"),
            "pass_b_sweeps": timings.get("stream_pass_b_sweeps"),
            "pass_b_tiles": timings.get("stream_pass_b_tiles"),
            "pass_b_reshipped_bytes": timings.get(
                "stream_pass_b_reshipped_bytes"),
        }
        log(f"## streamed percentiles: {n_rows} rows "
            f"({rec['stream_batches']} batches) in {dt:.1f}s; pass B "
            f"{rec['pass_b_sweeps']} sweep(s) over "
            f"{rec['pass_b_tiles']} tile(s) from {rec['pass_b_source']}"
            f", {rec['pass_b_reshipped_bytes']} bytes reshipped")
        emit(rec)

        # The multi-tile sweep path under an injected cap: budget for
        # 5/8 of one [P_pad, 1, span] block, so the planner must tile
        # AND pack (sweeps strictly below tiles on this shape). The
        # injection goes through the knob registry's seam-override
        # idiom — a mutated seam outranks any plan file, so this
        # record measures the injected cap even on an autotuned host.
        from pipelinedp_tpu import plan as plan_mod
        _, _, _, span = streaming_mod._tree_consts()
        P_pad = je._pad_pow2(parts)
        cap = max(4, (5 * P_pad) // 8) * span * 4
        with plan_mod.seam_override("subhist_byte_cap", cap):
            out2, dt2, t2 = run("capped")
        fields = ("percentile_50", "percentile_90", "percentile_99")
        parity = all(getattr(out2[p], f) == getattr(out[p], f)
                     for p in range(parts) for f in fields)
        rec2 = {
            "metric": "pass_b_sweep",
            "rows": n_rows,
            "partitions": parts,
            "subhist_cap_bytes": cap,
            "pass_b_tiles": t2.get("stream_pass_b_tiles"),
            "pass_b_tiles_per_sweep": t2.get(
                "stream_pass_b_tiles_per_sweep"),
            "pass_b_sweeps": t2.get("stream_pass_b_sweeps"),
            "pass_b_source": t2.get("stream_pass_b"),
            "pass_b_reshipped_bytes": t2.get(
                "stream_pass_b_reshipped_bytes"),
            "total_s": round(dt2, 3),
            "parity_vs_default_cap": "ok" if parity else "MISMATCH",
        }
        if not parity:
            log("## PASS-B SWEEP PARITY MISMATCH vs the default cap")
        log(f"## pass-B sweep (cap {cap >> 20} MiB): "
            f"{rec2['pass_b_sweeps']} sweeps over "
            f"{rec2['pass_b_tiles']} tiles "
            f"({rec2['pass_b_tiles_per_sweep']}/sweep) in {dt2:.1f}s, "
            f"parity {rec2['parity_vs_default_cap']}")
        emit(rec2)
        return rec, rec2
    finally:
        if did_set:
            if prev is None:
                os.environ.pop(streaming_mod._CHUNK_ENV, None)
            else:
                os.environ[streaming_mod._CHUNK_ENV] = prev


def bench_kernel_backend_compare(n_rows, smoke=False):
    """One-process A/B of the ``kernel_backend`` knob: the
    streamed-percentile workload (the pass-B multi-tile histogram
    binner, under a shrunken cap so the packed path actually runs) and
    the single-batch fused-aggregate workload (the lane-packed
    segment sum) each run warm under ``xla`` and ``pallas`` on the
    SAME data, with DP outputs cross-checked bit-for-bit. The record
    embeds both backends' rates and the per-phase ``device_costs``
    roofline verdicts (the cost observatory is force-enabled for the
    record's duration), so one artifact answers "did the hand-tiled
    kernels win here, and were they still bandwidth-bound". On the
    CPU proxy the Pallas interpret path is expected to LOSE — that is
    exactly the measurement the autotune sweep uses to self-select
    ``xla`` off-TPU."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import plan as plan_mod
    from pipelinedp_tpu import jax_engine as je
    from pipelinedp_tpu import streaming as streaming_mod
    from pipelinedp_tpu.obs import costs as obs_costs
    from pipelinedp_tpu.backends import JaxBackend

    rng = np.random.default_rng(19)
    parts = 60 if smoke else 600
    ds_q = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 1 << 16, n_rows).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n_rows).astype(np.float32))
    params_q = pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                 pdp.Metrics.PERCENTILE(99)],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    ds_f = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 1 << 16, n_rows).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n_rows).astype(np.float32))
    params_f = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN,
                 pdp.Metrics.VARIANCE],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    def run_streamed(ds):
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
        result = engine.aggregate(ds, params_q, pdp.DataExtractors(),
                                  public_partitions=list(range(parts)))
        acc.compute_budgets()
        with tracer().span("bench.kb_streamed", cat="bench") as sp:
            out = dict(result)
        return out, sp.duration

    def run_fused(ds):
        # Single-batch on purpose: this leg measures the engine's
        # fused_aggregate_kernel (the lane-packed segment sum's
        # "engine" phase), not the streamed path — lift the record's
        # chunk pin for its duration.
        chunk = os.environ.pop(streaming_mod._CHUNK_ENV, None)
        try:
            ds.invalidate_cache()
            n, dt, _ = run_once(JaxBackend(rng_seed=0), ds, params_f)
        finally:
            if chunk is not None:
                os.environ[streaming_mod._CHUNK_ENV] = chunk
        return n, dt

    _, _, _, span = streaming_mod._tree_consts()
    P_pad = je._pad_pow2(parts)
    # Budget for 5/8 of one [P_pad, 1, span] block: the sweep planner
    # must tile AND pack, so the multi-tile kernels (the Pallas
    # binner's dispatch point) run under BOTH backends.
    cap = max(4, (5 * P_pad) // 8) * span * 4
    prev_chunk = os.environ.get(streaming_mod._CHUNK_ENV)
    prev_costs = os.environ.get(obs_costs.ENV_VAR)
    os.environ[streaming_mod._CHUNK_ENV] = str(max(n_rows // 6, 1000))
    os.environ[obs_costs.ENV_VAR] = "1"
    sides = {}
    outputs = {}
    # The per-backend phase aggregates need a clean table per side,
    # but the table is PROCESS-global: every program entry captured by
    # the earlier bench configs (the PR 8 device_costs artifact) must
    # survive this record, so save everything and restore at the end.
    captured_programs = dict(obs_costs.TABLE.snapshot()["programs"])
    from pipelinedp_tpu.plan import knobs as plan_knobs
    spec = plan_knobs.BY_NAME["kernel_backend"]
    prev_backend = os.environ.get(spec.env_var)
    try:
        for backend in ("xla", "pallas"):
            # Pin each leg via the ENV override — the top of the
            # precedence chain. A seam set to the registry default
            # ("xla") is indistinguishable from "no override" and
            # would fall through to a plan file that may select
            # pallas, running BOTH legs on the same backend (the same
            # trap run_autotune's sweep isolation guards against).
            os.environ[spec.env_var] = backend
            with plan_mod.seam_override("subhist_byte_cap", cap):
                obs_costs.TABLE.reset()
                run_streamed(ds_q)          # warm (compile + capture)
                out_q, dt_q = run_streamed(ds_q)
                run_fused(ds_f)             # warm
                _, dt_f = run_fused(ds_f)
                snap = obs_costs.TABLE.snapshot()
                captured_programs.update(snap["programs"])
                phases = snap["phases"]
                sides[backend] = {
                    "streamed_percentile_rows_per_s": round(
                        n_rows / dt_q),
                    "streamed_s": round(dt_q, 3),
                    "fused_aggregate_rows_per_s": round(n_rows / dt_f),
                    "fused_s": round(dt_f, 3),
                    "device_costs": {
                        ph: {"verdict": agg.get("verdict"),
                             "intensity": agg.get("intensity")}
                        for ph, agg in sorted(phases.items())
                        if ph in ("engine", "pass_a", "pass_b")},
                }
                outputs[backend] = out_q
    finally:
        for var, prev in ((streaming_mod._CHUNK_ENV, prev_chunk),
                          (obs_costs.ENV_VAR, prev_costs),
                          (spec.env_var, prev_backend)):
            if prev is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prev
        # Restore the run-wide cost table (earlier configs' programs +
        # both backends' captures from this record) for the final run
        # report — the A/B resets must not erase run knowledge.
        obs_costs.TABLE.reset()
        for key, entry in captured_programs.items():
            obs_costs.TABLE.record(key, entry)
    fields = ("percentile_50", "percentile_90", "percentile_99")
    parity = all(
        getattr(outputs["pallas"][p], f) == getattr(outputs["xla"][p], f)
        for p in range(parts) for f in fields)
    if not parity:
        log("## KERNEL BACKEND PARITY MISMATCH (pallas vs xla)")
    rec = {
        "metric": "kernel_backend_compare",
        "rows": n_rows,
        "partitions": parts,
        "subhist_cap_bytes": cap,
        "backends": sides,
        "pallas_vs_xla_streamed": round(
            sides["pallas"]["streamed_percentile_rows_per_s"] /
            max(sides["xla"]["streamed_percentile_rows_per_s"], 1), 3),
        "pallas_vs_xla_fused": round(
            sides["pallas"]["fused_aggregate_rows_per_s"] /
            max(sides["xla"]["fused_aggregate_rows_per_s"], 1), 3),
        "parity": "ok" if parity else "MISMATCH",
        # This record ran BOTH backends; the stamp must not claim one.
        "kernel_backend": "both",
    }
    log(f"## kernel_backend compare [{n_rows} rows x {parts} parts]: "
        f"streamed xla "
        f"{sides['xla']['streamed_percentile_rows_per_s']} vs pallas "
        f"{sides['pallas']['streamed_percentile_rows_per_s']} rows/s "
        f"({rec['pallas_vs_xla_streamed']}x); fused xla "
        f"{sides['xla']['fused_aggregate_rows_per_s']} vs pallas "
        f"{sides['pallas']['fused_aggregate_rows_per_s']} rows/s "
        f"({rec['pallas_vs_xla_fused']}x); parity {rec['parity']}")
    emit(rec)
    return rec


def bench_mesh_topology_compare(n_rows, smoke=False):
    """One-process A/B of the ``mesh_topology`` knob on the 8-device
    CPU mesh: the same fused aggregation (count/sum/percentiles, same
    data, same seed) runs once over a ``flat`` mesh and once over a
    ``hier`` mesh with two SIMULATED hosts (``PIPELINEDP_TPU_MESH_
    HOSTS=2`` — the flat leg keeps the same host split, so its
    single-stage exchange bytes are attributed to DCN and the byte
    comparison is apples-to-apples). Released values are cross-checked
    BIT-FOR-BIT (the knob's dp-safety, PARITY row 43) and the analytic
    ``comms.dcn_bytes``/``comms.ici_bytes`` deltas of each side's cold
    (tracing) run are embedded. On the CPU proxy both topologies run in
    the same wall-clock class — the record's point is the byte
    asymmetry (``dcn_hier < dcn_flat``) plus the parity stamp, the
    evidence a real 2-host slice gates its topology choice on."""
    import jax

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import obs
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.parallel import sharded as psh
    from pipelinedp_tpu.plan import knobs as plan_knobs

    if len(jax.devices()) < 8:
        log("## mesh_topology compare SKIPPED (needs an 8-device mesh)")
        return None
    parts = 60 if smoke else 600
    ds = zipf_dataset(n_rows, max(n_rows // 20, 1_000), parts, seed=29)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                 pdp.Metrics.PERCENTILE(50),
                 pdp.Metrics.PERCENTILE(90)],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    def one(mesh):
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(mesh=mesh, rng_seed=0))
        res = engine.aggregate(ds, params, pdp.DataExtractors())
        acc.compute_budgets()
        with tracer().span("bench.mesh_topology", cat="bench") as sp:
            out = dict(res)
        return out, sp.duration

    spec = plan_knobs.BY_NAME["mesh_topology"]
    prev_topo = os.environ.get(spec.env_var)
    prev_hosts = os.environ.get(psh._MESH_HOSTS_ENV)
    sides, outputs = {}, {}
    try:
        os.environ[psh._MESH_HOSTS_ENV] = "2"
        for mode in ("flat", "hier"):
            # ENV pin, the top of the precedence chain — a plan file
            # must not flip one leg (run_autotune's isolation trap).
            os.environ[spec.env_var] = mode
            mesh = psh.make_mesh(8)
            topo = psh.topology_of(mesh)
            # The comms meter records at TRACE time: diff the counters
            # around the cold run (obs.reset() would erase the wider
            # bench run's spans, so diff instead of reset).
            before = dict(obs.ledger().snapshot()["counters"])
            out, cold_dt = one(mesh)
            after = dict(obs.ledger().snapshot()["counters"])
            _, warm_dt = one(mesh)

            def delta(name):
                return after.get(name, 0) - before.get(name, 0)

            sides[mode] = {
                "rows_per_s": round(n_rows / warm_dt),
                "warm_s": round(warm_dt, 3),
                "cold_s": round(cold_dt, 3),
                "topology": {"mode": topo.mode, "hosts": topo.n_hosts,
                             "per_host": topo.per_host,
                             "simulated_hosts": topo.simulated},
                "dcn_bytes": delta("comms.dcn_bytes"),
                "ici_bytes": delta("comms.ici_bytes"),
                "collectives": delta("comms.collectives"),
            }
            outputs[mode] = out
    finally:
        for var, prev in ((spec.env_var, prev_topo),
                          (psh._MESH_HOSTS_ENV, prev_hosts)):
            if prev is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prev
    parity = (set(outputs["flat"]) == set(outputs["hier"]) and all(
        outputs["flat"][k] == outputs["hier"][k]
        for k in outputs["flat"]))
    if not parity:
        log("## MESH TOPOLOGY PARITY MISMATCH (hier vs flat)")
    dcn_flat = sides["flat"]["dcn_bytes"]
    dcn_hier = sides["hier"]["dcn_bytes"]
    dcn_ok = dcn_flat > 0 and 0 < dcn_hier < dcn_flat
    if not dcn_ok:
        log(f"## mesh_topology compare: DCN byte asymmetry NOT "
            f"witnessed (flat={dcn_flat}, hier={dcn_hier} — a cached "
            f"trace records no bytes)")
    rec = {
        "metric": "mesh_topology_compare",
        "rows": n_rows,
        "partitions": parts,
        "devices": 8,
        "simulated_hosts": 2,
        "topologies": sides,
        "hier_vs_flat": round(
            sides["hier"]["rows_per_s"] /
            max(sides["flat"]["rows_per_s"], 1), 3),
        "dcn_bytes_flat": dcn_flat,
        "dcn_bytes_hier": dcn_hier,
        "dcn_reduction": (round(1.0 - dcn_hier / dcn_flat, 3)
                          if dcn_flat > 0 else None),
        "dcn_asymmetry": "ok" if dcn_ok else "NOT_WITNESSED",
        "parity": "ok" if parity else "MISMATCH",
        # This record ran BOTH topologies; the stamp must not claim
        # one (the kernel_backend_compare convention).
        "mesh_topology": "both",
    }
    log(f"## mesh_topology compare [{n_rows} rows x {parts} parts, "
        f"8 devices / 2 simulated hosts]: flat "
        f"{sides['flat']['rows_per_s']} vs hier "
        f"{sides['hier']['rows_per_s']} rows/s "
        f"({rec['hier_vs_flat']}x); dcn bytes {dcn_flat} -> "
        f"{dcn_hier} ({rec['dcn_reduction']} reduction); parity "
        f"{rec['parity']}")
    emit(rec)
    return rec


def bench_dp_vector_sum(n_rows, smoke=False):
    """``dp_vector_sum_rows_per_sec``: VECTOR_SUM at MXU-facing widths
    D in {64, 256, 1024}, streamed through the ingest ring under the
    fixed-point (``fx``) accumulator with the Pallas wide-D segment
    sum requested. Each width emits TWO rates — rows/s and coordinate
    bytes/s (D x 4 bytes of accumulated payload per row: the axis the
    wide-D tiling actually scales, where rows/s alone would reward
    narrow vectors) — plus the per-phase roofline verdicts from the
    cost observatory and the kernel dispatch evidence for the width
    (``kernel.pallas_dispatches`` delta, or the visible
    ``kernel.fallback`` reasons when the envelope refuses). Row counts
    shrink as D grows so every width moves a comparable coordinate
    payload. Both records stamp ``kernel_backend`` AND
    ``vector_accumulator``, so ``--compare`` refuses cross-backend or
    cross-accumulator gating instead of reporting a phantom
    regression."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import obs
    from pipelinedp_tpu import streaming as streaming_mod
    from pipelinedp_tpu.obs import costs as obs_costs
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.plan import knobs as plan_knobs

    widths = (64, 256, 1024)
    parts = 200 if smoke else 2_048
    rng = np.random.default_rng(29)
    acc_spec = plan_knobs.BY_NAME["vector_accumulator"]
    kb_spec = plan_knobs.BY_NAME["kernel_backend"]
    prev = {var: os.environ.get(var)
            for var in (streaming_mod._CHUNK_ENV, obs_costs.ENV_VAR,
                        acc_spec.env_var, kb_spec.env_var)}
    # ENV pins (the top of the knob precedence chain), same isolation
    # rationale as the kernel-backend A/B: a seam set to a default
    # would fall through to a loaded plan file.
    os.environ[obs_costs.ENV_VAR] = "1"
    os.environ[acc_spec.env_var] = "fx"
    os.environ[kb_spec.env_var] = "pallas"
    # The cost table is process-global; save the run's captures and
    # restore them after the per-width resets (same dance as the
    # kernel-backend record).
    captured_programs = dict(obs_costs.TABLE.snapshot()["programs"])
    recs = []
    try:
        for d in widths:
            # Constant coordinate payload across widths: D=1024 at the
            # D=64 row count would be a 16x larger array (8 GB at the
            # full-run size), and the interesting axis is D, not rows.
            n = max((n_rows * widths[0]) // d, 2_000)
            ds = pdp.ArrayDataset(
                privacy_ids=rng.integers(0, max(n // 8, 500), n),
                partition_keys=(rng.zipf(1.3, n) % parts).astype(
                    np.int32),
                values=rng.uniform(-1.0, 1.0,
                                   (n, d)).astype(np.float32))
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.VECTOR_SUM],
                noise_kind=pdp.NoiseKind.GAUSSIAN,
                max_partitions_contributed=4,
                max_contributions_per_partition=2,
                vector_size=d, vector_max_norm=4.0,
                vector_norm_kind=pdp.NormKind.L2)
            # Force the ingest ring at this width's row count: 4+
            # chunks so pass-A streams even at smoke sizes.
            os.environ[streaming_mod._CHUNK_ENV] = str(
                max(n // 4, 500))

            def run(ds):
                ds.invalidate_cache()
                acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                                total_delta=1e-6)
                engine = pdp.DPEngine(acc, JaxBackend(rng_seed=0))
                result = engine.aggregate(
                    ds, params, pdp.DataExtractors(),
                    public_partitions=list(range(parts)))
                acc.compute_budgets()
                with tracer().span("bench.vector_sum", cat="bench",
                                   d=d) as sp:
                    out = dict(result)
                return out, sp.duration

            obs_costs.TABLE.reset()
            before = obs.ledger().snapshot()
            run(ds)                     # warm (compile + capture)
            out, dt = run(ds)
            after = obs.ledger().snapshot()
            snap = obs_costs.TABLE.snapshot()
            captured_programs.update(snap["programs"])
            phases = snap["phases"]
            # Kernel dispatch evidence for THIS width: the dispatch
            # counter delta across both runs, and any segment_sum_wide
            # fallback reasons — one of the two must be visible.
            disp = (after["counters"].get("kernel.pallas_dispatches", 0)
                    - before["counters"].get("kernel.pallas_dispatches",
                                             0))
            n_old = len(before["events"])
            reasons = sorted({e.get("reason", "?")
                              for e in after["events"][n_old:]
                              if e["name"] == "kernel.fallback"
                              and e.get("site") == "segment_sum_wide"})
            rows_per_s = round(n / dt)
            coord_bytes_per_s = round(n * d * 4 / dt)
            common = {
                "d": d, "rows": n, "partitions": parts,
                "stream_s": round(dt, 3),
                "vector_accumulator": "fx",
                "kernel_backend": "pallas",
                "pallas_wide_dispatches": disp,
                "wide_fallback_reasons": reasons,
                "device_costs": {
                    ph: {"verdict": agg.get("verdict"),
                         "intensity": agg.get("intensity")}
                    for ph, agg in sorted(phases.items())
                    if ph in ("engine", "pass_a", "pass_b")},
            }
            rec = {"metric": "dp_vector_sum_rows_per_sec",
                   "value": rows_per_s, "unit": "rows/s", **common}
            log(f"## dp_vector_sum D={d} [{n} rows x {parts} parts]: "
                f"{rows_per_s} rows/s, {coord_bytes_per_s} "
                f"coord-bytes/s; pallas_wide_dispatches={disp}"
                + (f"; fallbacks={reasons}" if reasons else ""))
            emit(rec)
            recs.append(rec)
            # The companion rate in the width-scaled unit: same stamp
            # set, ``/s`` suffix, so --compare gates it identically.
            emit({"metric": "dp_vector_sum_coord_bytes_per_sec",
                  "value": coord_bytes_per_s, "unit": "coord-bytes/s",
                  **common})
    finally:
        for var, old in prev.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old
        obs_costs.TABLE.reset()
        for key, entry in captured_programs.items():
            obs_costs.TABLE.record(key, entry)
    return recs


def bench_serve_latency(n_rows, smoke=False):
    """``serve_request_latency`` record: a resident ``serve.Service``
    held warm across N sequential + M concurrent requests over three
    tenants. Reports the cold (first-request) wall, warm p50/p99
    request latency, sequential and concurrent requests/s — the
    serving-plane twin of the batch rows/s records. The headline value
    is the CONCURRENT requests/s (unit ``req/s``), so ``--compare``
    gates it like every other rate; ``plan_source``/``kernel_backend``
    stamps ride in through the shared emitter."""
    import shutil
    import tempfile

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import serve
    from pipelinedp_tpu.ingest.executor import _CaptureThread

    n_seq = 6 if smoke else 12
    n_conc = 8 if smoke else 16
    parts = 200 if smoke else 2_000
    rng = np.random.default_rng(23)
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, max(n_rows // 8, 1_000), n_rows),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int64),
        values=rng.uniform(0.0, 10.0, n_rows))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    tenants = {f"bench-t{i}": (1e6, 1e-3) for i in range(3)}

    def req(tenant, seed):
        return serve.ServeRequest(tenant=tenant, params=params,
                                  dataset=ds, epsilon=0.5, delta=1e-8,
                                  rng_seed=seed)

    def timed_submit(svc, tenant, seed):
        ds.invalidate_cache()
        with tracer().span("bench.serve_request", cat="bench",
                           tenant=tenant) as sp:
            out = svc.submit(req(tenant, seed))
        assert out.ok, f"serve refused: {out}"
        return sp.duration

    state_dir = tempfile.mkdtemp(prefix="pdp_serve_bench_")
    try:
        with serve.Service(state_dir, tenants=tenants,
                           max_queue=max(n_conc * 2, 16),
                           max_inflight_per_tenant=n_conc,
                           workers=4) as svc:
            names = sorted(tenants)
            cold_s = timed_submit(svc, names[0], seed=0)
            warm: list = []
            with tracer().span("bench.serve_sequential",
                               cat="bench") as seq_sp:
                for i in range(n_seq):
                    warm.append(timed_submit(svc, names[i % 3],
                                             seed=i + 1))
            warm.sort()
            p50 = warm[len(warm) // 2]
            p99 = warm[min(len(warm) - 1,
                           int(len(warm) * 0.99))]
            durations = [None] * n_conc

            def one(i):
                def body():
                    durations[i] = timed_submit(svc, names[i % 3],
                                                seed=100 + i)
                return _CaptureThread(body, f"pdp-serve-bench-{i}")

            with tracer().span("bench.serve_concurrent",
                               cat="bench") as conc_sp:
                threads = [one(i) for i in range(n_conc)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            for t in threads:
                if t.exc is not None:
                    raise t.exc
            conc_rps = n_conc / max(conc_sp.duration, 1e-9)
            seq_rps = n_seq / max(seq_sp.duration, 1e-9)
            conc_sorted = sorted(d for d in durations if d is not None)
            conc_p50 = (conc_sorted[len(conc_sorted) // 2]
                        if conc_sorted else 0.0)
    finally:
        shutil.rmtree(state_dir, ignore_errors=True)

    from pipelinedp_tpu import obs
    counters = obs.ledger().snapshot()["counters"]
    rec = {
        "metric": "serve_request_latency",
        "value": round(conc_rps, 2),
        "unit": "req/s",
        "rows_per_request": n_rows,
        "tenants": len(tenants),
        "sequential_requests": n_seq,
        "concurrent_requests": n_conc,
        "cold_s": round(cold_s, 4),
        "warm_p50_s": round(p50, 4),
        "warm_p99_s": round(p99, 4),
        "sequential_req_per_s": round(seq_rps, 2),
        "concurrent_p50_s": round(conc_p50, 4),
        "warm_hits": int(counters.get("serve.warm_hits", 0)),
        "cold_builds": int(counters.get("serve.cold_builds", 0)),
        # Execution mode, for --compare's cross-mode refusal: this
        # record always measures the solo (per-request-program) path.
        "fusion": False,
    }
    log(f"## serve_request_latency [{n_rows} rows x {parts} parts x "
        f"{len(tenants)} tenants]: cold {cold_s:.3f}s, warm p50 "
        f"{p50 * 1000:.1f}ms / p99 {p99 * 1000:.1f}ms, "
        f"{seq_rps:.1f} seq req/s, {conc_rps:.1f} concurrent req/s")
    emit(rec)
    return rec


def bench_serve_fused_throughput(n_rows, smoke=False):
    """``serve_fused_throughput`` record: the SAME 3-tenant workload as
    ``serve_request_latency``, served twice in one process — solo
    (fusion off: one compiled program per request) and fused (fusion
    on: the whole concurrent burst through ONE batched program per
    shape bucket) — with a same-seed bit-parity cross-check between
    the modes (PARITY row 35). The headline value is the FUSED
    concurrent requests/s (unit ``req/s`` so ``--compare`` gates it);
    the record carries the solo rate and the speedup, and is stamped
    ``fusion: true`` so cross-mode gating is refused (the
    plan_hash/kernel_backend refusals' twin)."""
    import shutil
    import tempfile

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import obs, serve
    from pipelinedp_tpu.ingest.executor import _CaptureThread

    n_conc = 8
    rounds = 2 if smoke else 3
    parts = 200 if smoke else 2_000
    rng = np.random.default_rng(23)
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, max(n_rows // 8, 1_000), n_rows),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int64),
        values=rng.uniform(0.0, 10.0, n_rows))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM, pdp.Metrics.MEAN],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    tenants = {f"bench-t{i}": (1e6, 1e-3) for i in range(3)}

    def req(i, seed):
        # A FRESH ArrayDataset per request (same column arrays, its
        # own cache): real traffic carries distinct per-request
        # payloads, so neither mode may ride another request's cached
        # encode or device placement — solo pays encode+ship per
        # request, fused pays encode per request and ONE ship per
        # batch, which is exactly the trade being measured.
        payload = pdp.ArrayDataset(privacy_ids=ds.privacy_ids,
                                   partition_keys=ds.partition_keys,
                                   values=ds.values)
        return serve.ServeRequest(tenant=f"bench-t{i % 3}",
                                  params=params, dataset=payload,
                                  epsilon=0.5, delta=1e-8,
                                  rng_seed=seed)

    def burst(svc, seed0):
        """One concurrent burst of n_conc submits; returns (wall_s,
        responses in submit order)."""
        outs = [None] * n_conc

        def one(i):
            def body():
                outs[i] = svc.submit(req(i, seed0 + i))
            return _CaptureThread(body, f"pdp-serve-bench-{i}")

        with tracer().span("bench.serve_burst", cat="bench") as sp:
            threads = [one(i) for i in range(n_conc)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for t in threads:
            if t.exc is not None:
                raise t.exc
        for out in outs:
            assert out.ok, f"serve refused: {out}"
        return sp.duration, outs

    def run_mode(fusion, seed0):
        state_dir = tempfile.mkdtemp(prefix="pdp_serve_fuse_bench_")
        try:
            with serve.Service(state_dir, tenants=tenants,
                               max_queue=max(n_conc * 2, 16),
                               max_inflight_per_tenant=n_conc,
                               workers=4, fusion=fusion,
                               fuse_window_ms=250,
                               fuse_max_batch=n_conc) as svc:
                # Warm-up burst: compiles the per-request programs
                # (solo) or the bucket's batched program (fused) — so
                # cold XLA compile stays out of the timed rounds — and
                # doubles as the parity cross-check: the SAME seeds run
                # through both modes, and in fused mode this burst
                # genuinely batches (n_conc concurrent same-bucket
                # submits flush as one fused batch), so the comparison
                # exercises the batched kernel, not a solo fallback.
                _, warm_outs = burst(svc, seed0)
                best = None
                for r in range(rounds):
                    wall, _ = burst(svc, seed0 + 100 * (r + 1))
                    best = wall if best is None else min(best, wall)
        finally:
            shutil.rmtree(state_dir, ignore_errors=True)
        return (n_conc / max(best, 1e-9),
                [dict(out.results) for out in warm_outs])

    def counter_delta(before, after, name):
        return int(after.get(name, 0)) - int(before.get(name, 0))

    # Counter DELTAS around each mode (never obs.reset(): the shared
    # ledger carries every earlier bench's spans for the final report).
    before = obs.ledger().snapshot()["counters"]
    solo_rps, solo_parity = run_mode(False, seed0=1_000)
    mid = obs.ledger().snapshot()["counters"]
    fused_rps, fused_parity = run_mode(True, seed0=1_000)
    after = obs.ledger().snapshot()["counters"]
    # The cross-check must not be vacuous: the seeded workload is
    # sized so selection keeps partitions.
    assert any(solo_parity), "parity burst released no partitions"
    parity_ok = all(
        set(s) == set(f) and all(tuple(s[k]) == tuple(f[k]) for k in s)
        for s, f in zip(solo_parity, fused_parity))
    rec = {
        "metric": "serve_fused_throughput",
        "value": round(fused_rps, 2),
        "unit": "req/s",
        "fusion": True,
        "rows_per_request": n_rows,
        "tenants": len(tenants),
        "concurrent_requests": n_conc,
        "rounds": rounds,
        "solo_req_per_s": round(solo_rps, 2),
        "speedup_vs_solo": round(fused_rps / max(solo_rps, 1e-9), 3),
        "parity_ok": bool(parity_ok),
        "fused_batches": counter_delta(mid, after,
                                       "serve.fused_batches"),
        "fused_requests": counter_delta(mid, after,
                                        "serve.fused_requests"),
        "fusion_fallbacks": counter_delta(mid, after,
                                          "serve.fusion_fallbacks"),
        "solo_requests_served": counter_delta(before, mid,
                                              "serve.requests_served"),
    }
    log(f"## serve_fused_throughput [{n_rows} rows x {parts} parts x "
        f"{n_conc} concurrent]: fused {fused_rps:.1f} req/s vs solo "
        f"{solo_rps:.1f} req/s ({rec['speedup_vs_solo']:.2f}x), "
        f"parity_ok={parity_ok}")
    assert parity_ok, (
        "fused-vs-solo same-seed outputs diverged — PARITY row 35 is "
        "broken; refusing to emit a throughput record for wrong bits")
    emit(rec)
    return rec


def bench_obs_overhead(n_rows, smoke=False):
    """``obs_overhead`` record: the SAME multi-tenant serve burst run
    twice in one process — once with the full observability plane
    armed (request-context tracing via ``PIPELINEDP_TPU_TRACE`` + the
    metrics registry + a LIVE ``/metrics`` endpoint scraped mid-run)
    and once with all of it off — with a same-seed bit-parity
    cross-check between the modes (the trace-context on/off PARITY
    row). The headline value is the INSTRUMENTED requests/s (unit
    ``req/s`` so ``--compare`` gates a regression in the traced path);
    the record carries the dark rate and the overhead fraction, which
    is the cost-of-observability claim made measurable."""
    import shutil
    import tempfile
    import urllib.request

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import serve
    from pipelinedp_tpu.ingest.executor import _CaptureThread

    n_conc = 4
    rounds = 2 if smoke else 3
    parts = 200 if smoke else 1_000
    rng = np.random.default_rng(29)
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, max(n_rows // 8, 1_000), n_rows),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int64),
        values=rng.uniform(0.0, 10.0, n_rows))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    tenants = {f"bench-t{i}": (1e6, 1e-3) for i in range(2)}

    def req(i, seed):
        payload = pdp.ArrayDataset(privacy_ids=ds.privacy_ids,
                                   partition_keys=ds.partition_keys,
                                   values=ds.values)
        return serve.ServeRequest(tenant=f"bench-t{i % 2}",
                                  params=params, dataset=payload,
                                  epsilon=0.5, delta=1e-8,
                                  rng_seed=seed)

    def burst(svc, seed0):
        outs = [None] * n_conc

        def one(i):
            def body():
                outs[i] = svc.submit(req(i, seed0 + i))
            return _CaptureThread(body, f"pdp-serve-bench-{i}")

        with tracer().span("bench.obs_burst", cat="bench") as sp:
            threads = [one(i) for i in range(n_conc)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for t in threads:
            if t.exc is not None:
                raise t.exc
        for out in outs:
            assert out.ok, f"serve refused: {out}"
        return sp.duration, outs

    def run_mode(instrumented, seed0):
        """One serve lifetime with observability fully on or fully
        dark; returns (req/s, warm-burst results, scrape bytes)."""
        saved = {k: os.environ.get(k)
                 for k in ("PIPELINEDP_TPU_TRACE",
                           "PIPELINEDP_TPU_METRICS_PORT")}
        if instrumented:
            os.environ["PIPELINEDP_TPU_TRACE"] = "1"
            os.environ["PIPELINEDP_TPU_METRICS_PORT"] = "0"
        else:
            os.environ.pop("PIPELINEDP_TPU_TRACE", None)
            os.environ.pop("PIPELINEDP_TPU_METRICS_PORT", None)
        state_dir = tempfile.mkdtemp(prefix="pdp_obs_overhead_bench_")
        scraped = 0
        try:
            with serve.Service(state_dir, tenants=tenants,
                               max_queue=max(n_conc * 2, 16),
                               max_inflight_per_tenant=n_conc,
                               workers=2) as svc:
                _, warm_outs = burst(svc, seed0)  # warm-up: compiles
                best = None
                for r in range(rounds):
                    wall, _ = burst(svc, seed0 + 100 * (r + 1))
                    best = wall if best is None else min(best, wall)
                if instrumented:
                    # A live scrape loop is part of the instrumented
                    # reality being priced, not a separate benchmark.
                    assert svc._http is not None, (
                        "metrics endpoint did not start")
                    url = f"{svc._http.url}/metrics"
                    with urllib.request.urlopen(url) as resp:
                        scraped = len(resp.read())
        finally:
            shutil.rmtree(state_dir, ignore_errors=True)
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return (n_conc / max(best, 1e-9),
                [dict(out.results) for out in warm_outs], scraped)

    dark_rps, dark_parity, _ = run_mode(False, seed0=7_000)
    on_rps, on_parity, scraped = run_mode(True, seed0=7_000)
    assert any(dark_parity), "parity burst released no partitions"
    parity_ok = all(
        set(d) == set(o) and all(tuple(d[k]) == tuple(o[k]) for k in d)
        for d, o in zip(dark_parity, on_parity))
    overhead = max(dark_rps / max(on_rps, 1e-9) - 1.0, 0.0)
    rec = {
        "metric": "obs_overhead_serve_req_per_s",
        "value": round(on_rps, 2),
        "unit": "req/s",
        "rows_per_request": n_rows,
        "tenants": len(tenants),
        "concurrent_requests": n_conc,
        "rounds": rounds,
        "dark_req_per_s": round(dark_rps, 2),
        "overhead_frac": round(overhead, 4),
        "metrics_scrape_bytes": int(scraped),
        "parity_ok": bool(parity_ok),
    }
    log(f"## obs_overhead [{n_rows} rows x {n_conc} concurrent]: "
        f"instrumented {on_rps:.1f} req/s vs dark {dark_rps:.1f} "
        f"req/s (overhead {overhead * 100:.1f}%), "
        f"parity_ok={parity_ok}")
    assert parity_ok, (
        "observability on/off same-seed outputs diverged — the "
        "trace-context parity row is broken; refusing to emit an "
        "overhead record for wrong bits")
    emit(rec)
    return rec


def bench_dp_heavy_hitters(n_rows, smoke=False):
    """DP heavy hitters over an unbounded STRING key space — the
    sketch-first two-phase path (``pipelinedp_tpu/sketch``): power-law
    synthetic URL-shaped keys (~n_rows/10 distinct strings, zipf mass)
    stream through a device counting sketch, DP bucket selection picks
    candidate heavy buckets, and the exact dense engine runs over only
    the candidates. The record carries the phase split (hash / bound /
    accumulate / select vs the exact pass), the candidate funnel
    (universe → selected buckets → candidates → released) and a
    top-50 recall diagnostic vs the true distinct-user ranking —
    stamped with fingerprint/plan/kernel-backend like every record so
    ``--compare`` gates it."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu.backends import JaxBackend

    distinct = max(n_rows // 10, 1_000)
    n_users = max(n_rows // 20, 1_000)
    rng = np.random.default_rng(23)
    raw = (rng.zipf(1.2, n_rows) % distinct).astype(np.int64)
    keys = np.char.add("url/", raw.astype("U12"))
    pids = rng.integers(0, n_users, n_rows)
    vals = rng.uniform(0.0, 10.0, n_rows)
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4,
        max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)
    sketch = pdp.SketchParams(
        eps=2.0, delta=1e-7,
        width=(1 << 12) if smoke else (1 << 16), depth=2,
        candidate_cap=256 if smoke else 2048)

    def one(seed, mesh=None):
        ds = pdp.ArrayDataset(privacy_ids=pids, partition_keys=keys,
                              values=vals)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(rng_seed=seed, mesh=mesh))
        res = engine.aggregate(ds, params, pdp.DataExtractors(),
                               sketch_first=sketch)
        acc.compute_budgets()
        with tracer().span("bench.dp_heavy_hitters", cat="bench") as sp:
            out = dict(res)
        return out, sp.duration, (res.timings or {})

    out, cold_dt, cold_timings = one(31)  # cold: XLA compiles inside
    single_out31 = out  # seed-31 release: the sharded parity anchor
    best = (out, cold_dt, cold_timings)
    for r in range(2):
        trial = one(31 + r)
        if trial[1] < best[1]:
            best = trial
    out, warm_dt, timings = best

    # True top-50 keys by distinct-user count (the utility target).
    pair = np.unique(pids.astype(np.int64) * distinct + raw)
    users_per_key = np.bincount((pair % distinct).astype(np.int64),
                                minlength=distinct)
    top50 = np.argsort(-users_per_key, kind="stable")[:50]
    top50_keys = {f"url/{k}" for k in top50.tolist()}
    recall = (sum(1 for k in top50_keys if k in out) /
              max(len(top50_keys), 1))

    rec = {
        "metric": "dp_heavy_hitters_rows_per_sec",
        "value": round(n_rows / warm_dt),
        "unit": "rows/s",
        "rows": n_rows,
        "distinct_keys": int(len(np.unique(raw))),
        "sketch_width": sketch.resolved_width(),
        "sketch_depth": sketch.resolved_depth(),
        "candidate_cap": sketch.resolved_candidate_cap(),
        "sketch_backend": sketch.resolved_backend(),
        "candidates": timings.get("sketch_candidates"),
        "released_partitions": len(out),
        "top50_recall": round(recall, 3),
        "warm_s": round(warm_dt, 3),
        "cold_s": round(cold_dt, 3),
        "sketch_hash_s": round(timings.get("sketch_hash_s", 0.0), 3),
        "sketch_bound_s": round(timings.get("sketch_bound_s", 0.0), 3),
        "sketch_accumulate_s": round(
            timings.get("sketch_accumulate_s", 0.0), 3),
        "sketch_select_s": round(
            timings.get("sketch_select_s", 0.0), 3),
        "exact_pass_device_s": round(timings.get("device_s", 0.0), 3),
    }
    log(f"## dp_heavy_hitters: {n_rows} rows x "
        f"{rec['distinct_keys']} distinct strings -> "
        f"{rec['candidates']} candidates -> {len(out)} released in "
        f"{warm_dt:.2f}s warm ({rec['value']} rows/s), top50 recall "
        f"{recall:.2f}")
    emit(rec)

    # Sharded variant: the same workload with the sketch phase's chunk
    # row axis sharded over the 8-device mesh (sketch/engine.py streams
    # through ``sharded_sketch_chunk_program`` — the phase-1 ceiling
    # removal), exact pass riding the same mesh. The sketch totals are
    # exact integers combined through the one exchange policy, so the
    # candidate FUNNEL must match the single-device seed-31 run
    # exactly: same candidate count, same released-partition set.
    # Released VALUES are compared per the mesh contract (tolerance,
    # not bits): per-device contribution bounding keeps a different —
    # equally valid — subset of each user's contributions at tight
    # bounds, so mesh-vs-single values are layout-dependent. Bit
    # parity is the hier-vs-flat guarantee, not mesh-vs-single.
    import jax

    from pipelinedp_tpu.parallel import sharded as psh
    if len(jax.devices()) >= 8:
        mesh = psh.make_mesh(8)
        sh_best = one(31, mesh=mesh)         # cold (compiles inside)
        sh_cold_dt = sh_best[1]
        trial = one(31, mesh=mesh)           # warm
        if trial[1] < sh_best[1]:
            sh_best = trial
        sh_out, sh_warm_dt, sh_timings = sh_best
        sh_parity = (
            set(sh_out) == set(single_out31)
            and sh_timings.get("sketch_candidates") ==
            cold_timings.get("sketch_candidates"))
        if not sh_parity:
            log("## DP HEAVY HITTERS SHARDED FUNNEL MISMATCH "
                "(8-device sketch vs single device: candidate count "
                "or released set diverged)")
        sh_rec = {
            "metric": "dp_heavy_hitters_sharded_rows_per_sec",
            "value": round(n_rows / sh_warm_dt),
            "unit": "rows/s",
            "rows": n_rows,
            "devices": 8,
            "sketch_topology": psh.topology_of(mesh).mode,
            "sketch_width": sketch.resolved_width(),
            "sketch_depth": sketch.resolved_depth(),
            "candidates": sh_timings.get("sketch_candidates"),
            "released_partitions": len(sh_out),
            "warm_s": round(sh_warm_dt, 3),
            "cold_s": round(sh_cold_dt, 3),
            "sketch_accumulate_s": round(
                sh_timings.get("sketch_accumulate_s", 0.0), 3),
            "parity": "ok" if sh_parity else "MISMATCH",
            "single_device_rows_per_s": rec["value"],
        }
        log(f"## dp_heavy_hitters sharded: {n_rows} rows over 8 "
            f"devices in {sh_warm_dt:.2f}s warm "
            f"({sh_rec['value']} rows/s vs {rec['value']} single), "
            f"parity {sh_rec['parity']}")
        emit(sh_rec)
    else:
        log("## dp_heavy_hitters sharded variant SKIPPED "
            "(needs an 8-device mesh)")
    return rec


def run_autotune(args):
    """``bench.py --autotune``: the bounded knob sweep that closes the
    measure→decide loop. Runs the streamed-percentile workload once per
    candidate knob vector (the default vector + one-factor deviations
    of every dp-safe knob — ``plan.autotune_candidates``), appends each
    trial to the run ledger as an ``autotune.trial`` entry, fits the
    stdlib cost model from the run-windowed entries (``--since-run-id``
    semantics: one windowed read after the sweep, never a full-ledger
    re-read per trial), and atomically writes the plan file a
    subsequent plain run resolves (``plan.applied`` events with
    ``source: "plan"``). Prints ONE JSON headline on stdout."""
    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import plan as plan_mod
    from pipelinedp_tpu import streaming as streaming_mod
    from pipelinedp_tpu.backends import JaxBackend
    from pipelinedp_tpu.obs import store as obs_store
    from pipelinedp_tpu.plan import model as plan_model

    n_rows = args.rows or 120_000
    parts = 60 if getattr(args, "smoke", False) else 3_000
    rng = np.random.default_rng(17)
    ds = pdp.ArrayDataset(
        privacy_ids=rng.integers(0, 1 << 20, n_rows).astype(np.int32),
        partition_keys=(rng.zipf(1.3, n_rows) % parts).astype(np.int32),
        values=rng.uniform(0.0, 10.0, n_rows).astype(np.float32))
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                 pdp.Metrics.PERCENTILE(99), pdp.Metrics.VARIANCE],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4, max_contributions_per_partition=2,
        min_value=0.0, max_value=10.0)

    # Sketch-first twin workload: the sketch_backend knob is only a
    # MEASURED choice if the sweep actually dispatches the sketch
    # binner — every trial runs the same small sketch-first request
    # inside its timed span, with the trial vector's backend, so the
    # base-vs-deviation argmin compares real matmul-vs-scatter work
    # (not timing noise) and every other deviation pays the identical
    # sketch cost.
    hh_rng = np.random.default_rng(29)
    hh_n = 8_000
    hh_keys = np.char.add("k/",
                          (hh_rng.zipf(1.3, hh_n) % 1000).astype("U6"))
    hh_pids = hh_rng.integers(0, 1000, hh_n)
    hh_params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT], noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=4,
        max_contributions_per_partition=2)

    def sketch_probe(vec):
        hh_acc = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                           total_delta=1e-6)
        hh_engine = pdp.DPEngine(hh_acc, JaxBackend(rng_seed=0))
        hh_res = hh_engine.aggregate(
            pdp.ArrayDataset(privacy_ids=hh_pids,
                             partition_keys=hh_keys, values=None),
            hh_params, pdp.DataExtractors(),
            sketch_first=pdp.SketchParams(
                eps=2.0, delta=1e-7, width=2048, depth=2,
                candidate_cap=512,
                backend=str(vec.get("sketch_backend", "xla"))))
        hh_acc.compute_budgets()
        dict(hh_res)

    # Megasweep twin workload: the sweep_config_batch knob is only a
    # MEASURED choice if the trial actually dispatches the config-
    # batched sweep kernels — every trial runs the same small
    # utility-analysis grid inside its timed span with the trial
    # vector's batch width in force (via the seam; the sweep phase
    # feeds the trial's ``phases`` dict, which plan/model.py's fit
    # consumes), so the base-vs-deviation argmin compares measured
    # walked-vs-batched dispatch behavior and every other deviation
    # pays the identical sweep cost.
    from pipelinedp_tpu import analysis as analysis_mod
    sw_rng = np.random.default_rng(31)
    sw_n = 30_000
    sw_ds = pdp.ArrayDataset(
        privacy_ids=sw_rng.integers(0, 4_000, sw_n),
        partition_keys=(sw_rng.zipf(1.3, sw_n) % 200).astype(np.int64),
        values=sw_rng.uniform(0.0, 10.0, sw_n))
    sw_pairs = [(a, b) for a in range(1, 5) for b in range(1, 5)]
    sw_options = analysis_mod.UtilityAnalysisOptions(
        epsilon=1.0, delta=1e-6,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=2),
        multi_param_configuration=(
            analysis_mod.MultiParameterConfiguration(
                max_partitions_contributed=[p[0] for p in sw_pairs],
                max_contributions_per_partition=[p[1]
                                                 for p in sw_pairs])))

    def sweep_probe(vec):
        with plan_mod.seam_override(
                "sweep_config_batch",
                int(vec.get("sweep_config_batch", 0))):
            with tracer().span("autotune.sweep_probe",
                               cat="autotune") as sp:
                list(analysis_mod.perform_utility_analysis(
                    sw_ds, JaxBackend(rng_seed=0), sw_options,
                    pdp.DataExtractors()))
        return sp.duration

    led = _bench_ledger()
    # Pre-sweep end offset of the ledger file: the post-sweep fit reads
    # only the bytes appended after this point (read_from), so fitting
    # stays O(sweep) on a long-lived service ledger instead of
    # re-parsing the whole history every autotune.
    sweep_offset = 0
    if led._store is not None:
        try:
            sweep_offset = os.path.getsize(led._store.path)
        except OSError:
            sweep_offset = 0
    # The sweep measures the TRIAL vectors: a plan file left by a prior
    # autotune must not steer them. A seam pinned AT the registry
    # default is indistinguishable from "no override" (the precedence
    # falls through to the plan), so the default-vector trial and every
    # single-knob deviation would silently execute the old plan while
    # the ledger labels them with the trial's knobs. Disable plan
    # loading for the sweep's duration; the write at the end needs the
    # real directory back, so the restore sits in the same finally as
    # the chunk env.
    from pipelinedp_tpu.plan import planner as planner_mod
    prev_plan_dir = os.environ.get(planner_mod.ENV_DIR)
    os.environ[planner_mod.ENV_DIR] = "0"
    plan_mod.reset()
    prev = os.environ.get(streaming_mod._CHUNK_ENV)
    did_set = False
    if n_rows <= streaming_mod.stream_chunk_rows():
        # The sweep must exercise the streamed path (that is where the
        # knobs live): force a chunk below the dataset, exactly like
        # the streamed-percentile bench record.
        os.environ[streaming_mod._CHUNK_ENV] = str(max(n_rows // 6,
                                                       1000))
        did_set = True
    shape = {"rows": n_rows, "partitions": parts, "quantiles": 3}
    log(f"## autotune: {n_rows} rows x {parts} partitions, "
        f"{len(plan_mod.autotune_candidates())} candidate vectors")
    trials = []

    def one_run(vec):
        ds.invalidate_cache()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, JaxBackend(
            rng_seed=0,
            ingest_executor=bool(vec["ingest_executor"]),
            stream_cache=int(vec["stream_cache_bytes"])))
        result = engine.aggregate(ds, params, pdp.DataExtractors(),
                                  public_partitions=list(range(parts)))
        acc.compute_budgets()
        with plan_mod.seam_override("subhist_byte_cap",
                                    vec["subhist_byte_cap"]), \
                plan_mod.seam_override("q_chunk", vec["q_chunk"]), \
                plan_mod.seam_override("kernel_backend",
                                       vec.get("kernel_backend",
                                               "xla")):
            with tracer().span("autotune.trial", cat="autotune") as sp:
                dict(result)
                sketch_probe(vec)
                sweep_s = sweep_probe(vec)
        return sp.duration, result.timings or {}, sweep_s

    try:
        candidates = plan_mod.autotune_candidates()
        # Untimed warm-up of EACH candidate immediately before its
        # timed run: different vectors select different XLA programs
        # (a shrunken cap forces the multi-tile kernels, a q_chunk pin
        # a different tile grid), so one default-vector warm-up would
        # leave every deviation paying cold compile inside its timed
        # window and bias the measured argmin toward the default.
        for i, vec in enumerate(candidates):
            one_run(vec)
            dt, timings, sweep_s = one_run(vec)
            trial = {
                "index": i,
                "knobs": {k: (int(v) if isinstance(v, bool) else v)
                          for k, v in vec.items()},
                "shape": shape,
                "device_kind": env_fingerprint().get("device_kind"),
                "total_s": round(dt, 4),
                "rows_per_s": round(n_rows / dt),
                "phases": {
                    "pass_a": timings.get("stream_t_total"),
                    "pass_b": timings.get("stream_pass_b_sweep_s"),
                    "sweep": round(sweep_s, 4),
                },
                "pass_b_sweeps": timings.get("stream_pass_b_sweeps"),
            }
            trials.append(trial)
            led.append("autotune.trial", {"trial": trial,
                                          "env": env_fingerprint()})
            log(f"## autotune trial {i}: {trial['knobs']} -> "
                f"{trial['total_s']}s ({trial['rows_per_s']} rows/s)")
    finally:
        if did_set:
            if prev is None:
                os.environ.pop(streaming_mod._CHUNK_ENV, None)
            else:
                os.environ[streaming_mod._CHUNK_ENV] = prev
        if prev_plan_dir is None:
            os.environ.pop(planner_mod.ENV_DIR, None)
        else:
            os.environ[planner_mod.ENV_DIR] = prev_plan_dir
        plan_mod.reset()

    # ONE windowed ledger read after the sweep: only the bytes past
    # the pre-sweep offset, then THIS run's entries only — a
    # concurrent sweep sharing the ledger appends its own trials
    # interleaved with ours, and a trial measured under another
    # process's env must never win a bucket in this process's plan.
    fresh = (led._store.read_from(sweep_offset)[0]
             if led._store is not None else [])
    entries = [e for e in obs_store.entries_since_run_id(fresh,
                                                         led.run_id)
               if e.get("run_id") == led.run_id]
    model = plan_model.fit(entries, fingerprint=led.fingerprint)
    best = plan_model.choose_best_trial(entries,
                                        fingerprint=led.fingerprint)
    headline = {"metric": "autotune", "trials": len(trials),
                "rows": n_rows, "partitions": parts,
                "degraded": bool(os.environ.get(
                    "PIPELINEDP_TPU_DEGRADED")),
                "env": env_fingerprint()}
    if best is None:
        # Every trial degraded or failed: refuse to write a plan from
        # poisoned measurements — the next run keeps the defaults.
        headline["plan_file"] = None
        log("## autotune: no eligible (non-degraded) trials — no plan "
            "written, defaults stay in force")
    else:
        plan = plan_mod.build_plan(
            best, model,
            device_kind=env_fingerprint().get("device_kind"),
            trials=len(trials))
        path = plan_mod.write_plan(plan)
        headline["plan_file"] = path
        headline["plan_hash"] = plan_mod.plan_hash(plan)
        headline["best"] = {b: row["knobs"]
                            for b, row in best.items()}
        log(f"## autotune: plan {headline['plan_hash']} written to "
            f"{path} from {len(trials)} trial(s)")
    record_run_report()
    print(json.dumps(headline))
    return 0


def roofline_probe(ds):
    """Roofline numbers for the fused kernel's dominant device ops on this
    chip: the 3-key lexsort and one per-pk segment_sum, reported as
    achieved bytes/s against the v5e HBM peak (~810 GB/s). The sort's
    traffic model is a bitonic network: ~log2(n)(log2(n)+1)/2 stages,
    each reading+writing 4 operands (3 sort keys + the index payload) of
    4 bytes."""
    import math

    import jax
    import jax.numpy as jnp

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import jax_engine
    from pipelinedp_tpu.ops import segment as seg_ops

    enc = jax_engine.encode(ds, pdp.DataExtractors(), None)
    pid, pk, _, _ = jax_engine.pad_and_put(enc, None, with_values=False)
    n = int(pid.shape[0])
    key = jax.random.PRNGKey(0)

    @jax.jit
    def sort_only(pid, pk, key):
        k_tie, k_salt = jax.random.split(key)
        salt = jax.random.bits(k_salt, (), dtype=jnp.uint32)
        tie = jax.random.bits(k_tie, (n,), dtype=jnp.uint32)
        hpk = seg_ops.fmix32(
            seg_ops.fmix32(pid.astype(jnp.uint32) ^ salt) ^
            pk.astype(jnp.uint32))
        return jnp.lexsort((tie, hpk, pid))[0]

    @jax.jit
    def segsum_only(pk):
        return jax.ops.segment_sum(jnp.ones_like(pk), pk,
                                   num_segments=65536)[0]

    def timed(fn, *args, label="op"):
        best = 1e9
        for _ in range(3):
            # np.asarray forces execution + flush (block_until_ready
            # does not flush on the tunneled platform).
            with tracer().span(f"roofline.{label}",
                               cat="roofline") as sp:
                np.asarray(fn(*args))
            best = min(best, sp.duration)
        return best

    # Quantile-walk pieces at bench shape: the per-quantile relevance
    # flags + prefix-sum compaction (the r5 sub-histogram path: one
    # packed-block gather + byte compares per 4 quantiles, a cumsum of
    # the flags and two monotone int32 scatters into the n/8 prefix —
    # replacing the former stable argsort's bitonic network) and one
    # [P, 256] top-histogram scatter. Traffic models: flags read
    # qpk+leaf+1 gather word and write 1 byte (~13 B/row); cumsum +
    # dest + 2 scatters are ~4 more int32 passes (~16 B/row); the
    # top-hist scatter reads key+payload and read-modify-writes its
    # output (~16 B/row).
    P_walk = 1 << 17
    Q = 3
    blk = jax.random.randint(jax.random.fold_in(key, 1), (P_walk, Q), 0,
                             255, jnp.int32)
    leaf = jax.random.randint(jax.random.fold_in(key, 2), (n,), 0, 65536,
                              jnp.int32)
    qpk = jnp.abs(pk) % P_walk

    @jax.jit
    def walk_flags_and_sort(qpk, leaf, blk):
        packed = blk[:, 0] | (blk[:, 1] << 8) | (blk[:, 2] << 16)
        pr = packed[qpk]
        mid = leaf >> 8
        rel_any = ((mid == (pr & 0xFF)) | (mid == ((pr >> 8) & 0xFF)) |
                   (mid == ((pr >> 16) & 0xFF)))
        cap = max(8192, n // 8)
        dest = jnp.where(rel_any,
                         jnp.cumsum(rel_any.astype(jnp.int32)) - 1, cap)
        qpk_c = jnp.zeros(cap, jnp.int32).at[dest].set(qpk, mode="drop")
        row_c = jnp.zeros(cap, jnp.int32).at[dest].set(leaf, mode="drop")
        return qpk_c[0] + row_c[0]

    @jax.jit
    def top_hist(qpk, leaf):
        return jax.ops.segment_sum(
            jnp.ones_like(qpk), qpk * 256 + (leaf >> 8),
            num_segments=P_walk * 256)[0]

    sort_only(pid, pk, key)
    segsum_only(pk)
    walk_flags_and_sort(qpk, leaf, blk)
    top_hist(qpk, leaf)
    sort_s = timed(sort_only, pid, pk, key, label="sort")
    seg_s = timed(segsum_only, pk, label="segment_sum")
    walk_s = timed(walk_flags_and_sort, qpk, leaf, blk,
                   label="walk_flags")
    hist_s = timed(top_hist, qpk, leaf, label="top_hist")
    stages = math.log2(n) * (math.log2(n) + 1) / 2
    sort_bytes = stages * n * 16 * 2
    hbm_peak = 810e9
    walk_bytes = n * (13 + 16)  # flags + cumsum/dest/2-scatter passes
    hist_bytes = n * 16
    rec = {
        "metric": "roofline",
        "rows": n,
        "sort_s": round(sort_s, 4),
        "sort_model_gb": round(sort_bytes / 1e9, 1),
        "sort_gb_per_s": round(sort_bytes / sort_s / 1e9, 1),
        "sort_hbm_frac": round(sort_bytes / sort_s / hbm_peak, 3),
        "segment_sum_s": round(seg_s, 4),
        "segment_sum_gb_per_s": round(n * 8 * 2 / seg_s / 1e9, 1),
        "walk_flag_sort_s": round(walk_s, 4),
        "walk_flag_sort_gb_per_s": round(walk_bytes / walk_s / 1e9, 1),
        "walk_flag_sort_hbm_frac": round(
            walk_bytes / walk_s / hbm_peak, 3),
        "walk_hist_scatter_s": round(hist_s, 4),
        "walk_hist_scatter_gb_per_s": round(
            hist_bytes / hist_s / 1e9, 1),
        "walk_hist_scatter_hbm_frac": round(
            hist_bytes / hist_s / hbm_peak, 3),
    }
    log(f"## roofline: sort {sort_s:.3f}s ({rec['sort_gb_per_s']} GB/s, "
        f"{rec['sort_hbm_frac']:.0%} of HBM peak), segment_sum "
        f"{seg_s:.3f}s, walk flags+compaction {walk_s:.3f}s "
        f"({rec['walk_flag_sort_hbm_frac']:.0%} of peak), walk top-hist "
        f"scatter {hist_s:.3f}s "
        f"({rec['walk_hist_scatter_hbm_frac']:.0%} of peak)")
    emit(rec)
    return rec


def walk_breakdown_probe(n_partitions, n_rows, n_quantiles=3):
    """Per-phase breakdown of the quantile walk at the config-4 shape,
    mirroring the ingest record's ``t_stage/t_fold/t_device/t_total``
    split: ``t_noise`` (the per-level node-noise generation alone — the
    counter-based threefry draws, 4 levels with the root deduped),
    ``t_hist`` (the [P, 256] top-histogram row scatter — the walk's one
    unconditional full-row scatter; the data-dependent compacted
    subtree build lands in the residual), ``t_walk`` (the residual,
    t_total minus the other two, floored at 0) and
    ``t_total`` (the full ``_percentile_values`` wall clock). Driver-
    measurable: re-deriving the node-noise speedup claim needs exactly
    one clean run of this record before and after a change."""
    import jax
    import jax.numpy as jnp

    from pipelinedp_tpu import jax_engine as je
    from pipelinedp_tpu.aggregate_params import NoiseKind
    from pipelinedp_tpu.ops import quantile_tree as qt

    P = je._pad_pow2(n_partitions)
    n = je._pad_rows(n_rows)
    b = qt.DEFAULT_BRANCHING_FACTOR
    height = qt.DEFAULT_TREE_HEIGHT
    n_leaves = b**height
    Q = n_quantiles
    percentiles = tuple(float(p) for p in
                        np.linspace(50, 99, Q).round(0))
    config = je.FusedConfig(
        metrics=("PERCENTILE",), percentiles=percentiles,
        noise_kind=NoiseKind.LAPLACE, linf=2, l0=4,
        per_partition_bounds=False, min_value=0.0, max_value=10.0,
        min_sum_per_partition=None, max_sum_per_partition=None,
        vector_size=None, vector_norm_kind=None, vector_max_norm=None,
        selection=None, bounds_already_enforced=False)
    key = jax.random.PRNGKey(0)
    qpk = jax.random.randint(jax.random.fold_in(key, 1), (n,), 0,
                             n_partitions, jnp.int32)
    leaf = jax.random.randint(jax.random.fold_in(key, 2), (n,), 0,
                              n_leaves, jnp.int32)
    kept = jnp.ones(n, bool)
    scale = jnp.float32(2.0)
    bucket_w = b**(height - 2)  # the top histogram's bucket width

    @jax.jit
    def noise_only(key):
        # The walk's exact per-level draw structure: root deduped to
        # [P, 1, b] and broadcast, three [P, Q, b] levels below.
        tot = jnp.float32(0)
        base = jnp.zeros((P, Q), jnp.int32)
        level_offset = 0
        for level in range(height):
            node_ids = (level_offset + base)[..., None] + jnp.arange(
                b, dtype=jnp.int32)
            ids = node_ids[:, :1, :] if level_offset == 0 else node_ids
            tot += je._node_noise(config.noise_kind, key, ids).sum()
            level_offset += b**(level + 1)
        return tot

    @jax.jit
    def hist_only(qpk, leaf, kept):
        # The [P, b^2] top-histogram scatter — the walk's one
        # unconditional full-row scatter (the bottom-level sub-histogram
        # build is data-dependent: prefix-sum compaction makes its cost
        # a function of subtree concentration, so it lands in the
        # t_walk residual rather than being modeled separately).
        n_mid = b * b
        hist = jax.ops.segment_sum(
            kept.astype(jnp.int32),
            qpk * n_mid + jnp.minimum(leaf // bucket_w, n_mid - 1),
            num_segments=P * n_mid)
        return hist[0]

    @jax.jit
    def walk_full(qpk, leaf, kept, scale, key):
        return je._percentile_values(config, P, (qpk, leaf, kept),
                                     scale, key)[0, 0]

    def timed(fn, *args, label="phase"):
        np.asarray(fn(*args))  # compile warm-up
        best = 1e9
        for _ in range(3):
            # The acceptance spans for the three walk phases: each
            # repetition is one "walk.<phase>" span in the ledger.
            with tracer().span(f"walk.{label}", cat="walk") as sp:
                np.asarray(fn(*args))
            best = min(best, sp.duration)
        return best

    t_noise = timed(noise_only, key, label="noise")
    t_hist = timed(hist_only, qpk, leaf, kept, label="hist")
    t_total = timed(walk_full, qpk, leaf, kept, scale, key,
                    label="walk")
    rec = {
        "metric": "quantile_walk_breakdown",
        "partitions": P,
        "rows": n,
        "quantiles": Q,
        "t_noise": round(t_noise, 4),
        "t_hist": round(t_hist, 4),
        "t_walk": round(max(0.0, t_total - t_noise - t_hist), 4),
        "t_total": round(t_total, 4),
    }
    log(f"## quantile walk breakdown [{P} parts, {n} rows, {Q} q]: "
        f"noise {t_noise:.3f}s + hist {t_hist:.3f}s + walk "
        f"{rec['t_walk']:.3f}s (total {t_total:.3f}s)")
    emit(rec)
    return rec


def record_run_report(snapshot=None):
    """Build this run's schema-v2 run report (env fingerprint + spans +
    counters/events + the privacy audit section) and append it to the
    store as the ``run_report`` entry — the span-total baseline future
    ``--compare`` runs diff against. Returns the report."""
    from pipelinedp_tpu import obs
    report = obs.build_run_report(env=env_fingerprint(),
                                  snapshot=snapshot)
    _bench_ledger().append("run_report",
                           {"run_report": report, "env": env_fingerprint()})
    return report


def compare_to_baseline(records=None, run_report=None, threshold=0.10):
    """The regression gate behind ``--compare``: diff this run's
    headline rates (every record with a ``.../s`` unit) and span totals
    against the store's last-known-good entries for the SAME environment
    fingerprint. Degraded baselines are never used — when a newer
    degraded capture is passed over, a ``bench.compare_skipped_degraded``
    event goes on the record. Returns the artifact's ``regressions``
    section; ``regressed`` lists metrics whose rate dropped more than
    ``threshold`` (the ``--strict`` exit condition)."""
    from pipelinedp_tpu import obs
    led = _bench_ledger()
    records = _RUN_RECORDS if records is None else records
    rates, spans, regressed = [], [], []
    skipped_degraded = 0
    plan_mismatches = 0
    backend_mismatches = 0
    fusion_mismatches = 0
    accumulator_mismatches = 0
    sweep_batch_mismatches = 0
    topology_mismatches = 0
    cur_plan = plan_provenance()
    cur_backend = kernel_backend_in_force()
    # One comparison per metric, at its BEST value this run — the same
    # best-sample rule the headline applies (the flagship re-sample
    # emits the metric twice; a slow-window sample must not fail a gate
    # the headline passed).
    best, order = {}, []
    for rec in records:
        value = rec.get("value")
        unit = rec.get("unit") or ""
        if not isinstance(value, (int, float)) or not unit.endswith("/s"):
            continue
        prev = best.get(rec["metric"])
        if prev is None:
            order.append(rec["metric"])
            best[rec["metric"]] = rec
        elif value > prev["value"]:
            best[rec["metric"]] = rec
    for name in order:
        rec = best[name]
        value = rec["value"]
        base, skipped = led.baseline(rec["metric"])
        if skipped:
            skipped_degraded += 1
            obs.inc("bench.compare_skipped_degraded")
            obs.event("bench.compare_skipped_degraded",
                      metric=rec["metric"], fingerprint=led.fingerprint)
            log(f"## compare: skipped a DEGRADED newer capture of "
                f"{rec['metric']} (never a baseline)")
        base_val = None
        if base is not None:
            base_val = ((base.get("payload") or {}).get("record")
                        or {}).get("value")
        if not isinstance(base_val, (int, float)) or base_val <= 0:
            rates.append({"metric": rec["metric"], "current": value,
                          "baseline": None})
            continue
        entry = {"metric": rec["metric"], "current": value,
                 "baseline": base_val,
                 "ratio": round(value / base_val, 3),
                 "baseline_ts": base.get("ts")}
        # Plan-provenance gate: a run under a different knob REGIME
        # than its baseline — a plan-hash change, or an env/seam
        # override vs a default baseline (both hash None, so the
        # source label is the only tell: the env fingerprint's stable
        # fields exclude the PIPELINEDP_TPU_* flags) — measures two
        # different knob vectors, and a rate delta there is a plan
        # difference, not a regression. Refuse to gate instead of
        # crying wolf; the mismatch is recorded and the verdict line
        # says so. Absent fields on old records read as "no plan"
        # (pre-planner), so default-vs-default keeps gating exactly as
        # before.
        base_rec = (base.get("payload") or {}).get("record") or {}
        base_plan = {"plan_source": base_rec.get("plan_source",
                                                 "default"),
                     "plan_hash": base_rec.get("plan_hash")}
        cur_hash = rec.get("plan_hash", cur_plan["plan_hash"])
        cur_source = rec.get("plan_source", cur_plan["plan_source"])
        if (base_plan["plan_hash"] != cur_hash
                or base_plan["plan_source"] != cur_source):
            plan_mismatches += 1
            entry["plan_mismatch"] = True
            entry["baseline_plan"] = base_plan
            obs.inc("bench.compare_plan_mismatch")
            obs.event("bench.compare_plan_mismatch",
                      metric=rec["metric"],
                      baseline_source=base_plan["plan_source"],
                      current_source=cur_source)
            log(f"## compare: plan mismatch on {rec['metric']} "
                f"(baseline {base_plan['plan_source']}/"
                f"{base_plan['plan_hash']}, this run "
                f"{cur_source}/{cur_hash}) — not gated")
            rates.append(entry)
            continue
        # Kernel-backend gate (the plan_hash refusal's twin): an xla
        # rate gated against a pallas baseline (or vice versa)
        # compares two different device programs. Absent fields on
        # old records read as "xla" (the pre-knob behavior), so
        # xla-vs-old keeps gating exactly as before.
        base_backend = base_rec.get("kernel_backend", "xla")
        rec_backend = rec.get("kernel_backend", cur_backend)
        if base_backend != rec_backend:
            backend_mismatches += 1
            entry["kernel_backend_mismatch"] = True
            entry["baseline_kernel_backend"] = base_backend
            obs.inc("bench.compare_kernel_backend_mismatch")
            obs.event("bench.compare_kernel_backend_mismatch",
                      metric=rec["metric"],
                      baseline_backend=base_backend,
                      current_backend=rec_backend)
            log(f"## compare: kernel-backend mismatch on "
                f"{rec['metric']} (baseline {base_backend}, this run "
                f"{rec_backend}) — not gated")
            rates.append(entry)
            continue
        # Mesh-topology gate (the kernel_backend refusal's twin): a
        # flat-exchange rate gated against a hierarchical baseline (or
        # vice versa) compares two different collective schedules —
        # released values are bit-identical (PARITY row 43), but the
        # rate delta is a topology difference, not a regression.
        # Absent fields on old records read as "flat" (the pre-knob
        # behavior), so flat-vs-old keeps gating exactly as before.
        base_topo = base_rec.get("mesh_topology", "flat")
        rec_topo = rec.get("mesh_topology", "flat")
        if base_topo != rec_topo:
            topology_mismatches += 1
            entry["mesh_topology_mismatch"] = True
            entry["baseline_mesh_topology"] = base_topo
            obs.inc("bench.compare_mesh_topology_mismatch")
            obs.event("bench.compare_mesh_topology_mismatch",
                      metric=rec["metric"],
                      baseline_topology=base_topo,
                      current_topology=rec_topo)
            log(f"## compare: mesh-topology mismatch on "
                f"{rec['metric']} (baseline {base_topo}, this run "
                f"{rec_topo}) — not gated")
            rates.append(entry)
            continue
        # Vector-accumulator gate (the kernel_backend refusal's twin,
        # for the vector records): an ``fx`` rate gated against an
        # ``f32`` baseline (or vice versa) compares exact integer
        # accumulation against float accumulation — a different device
        # program AND different released bits. Absent fields (old or
        # scalar records) read as "" on both sides, so everything
        # without the knob keeps gating exactly as before.
        base_acc = base_rec.get("vector_accumulator", "")
        rec_acc = rec.get("vector_accumulator", "")
        if base_acc != rec_acc:
            accumulator_mismatches += 1
            entry["vector_accumulator_mismatch"] = True
            entry["baseline_vector_accumulator"] = base_acc
            obs.inc("bench.compare_vector_accumulator_mismatch")
            obs.event("bench.compare_vector_accumulator_mismatch",
                      metric=rec["metric"],
                      baseline_accumulator=base_acc,
                      current_accumulator=rec_acc)
            log(f"## compare: vector-accumulator mismatch on "
                f"{rec['metric']} (baseline "
                f"{base_acc or 'none'}, this run "
                f"{rec_acc or 'none'}) — not gated")
            rates.append(entry)
            continue
        # Sweep-config-batch gate (the kernel_backend refusal's twin,
        # for the megasweep records): a width-256 configs/s rate gated
        # against a width-16 baseline compares two different dispatch
        # regimes of the same kernel — ceil(K/width) dispatches each —
        # and grids of different size besides. The outputs are
        # bit-identical per config at every width (PARITY row 41), so
        # only the RATE comparison is meaningless, never the results.
        # Absent fields (old or non-megasweep records) read as "" on
        # both sides, so everything without the stamp keeps gating
        # exactly as before.
        base_scb = base_rec.get("sweep_config_batch", "")
        rec_scb = rec.get("sweep_config_batch", "")
        if base_scb != rec_scb:
            sweep_batch_mismatches += 1
            entry["sweep_config_batch_mismatch"] = True
            entry["baseline_sweep_config_batch"] = base_scb
            obs.inc("bench.compare_sweep_config_batch_mismatch")
            obs.event("bench.compare_sweep_config_batch_mismatch",
                      metric=rec["metric"],
                      baseline_batch=base_scb,
                      current_batch=rec_scb)
            log(f"## compare: sweep-config-batch mismatch on "
                f"{rec['metric']} (baseline "
                f"{base_scb or 'none'}, this run "
                f"{rec_scb or 'none'}) — not gated")
            rates.append(entry)
            continue
        # Fusion-mode gate (the kernel_backend refusal's twin, for the
        # serving records): a fused req/s rate gated against a solo
        # baseline (or vice versa) compares two execution modes — one
        # program per request vs one program per batch. Absent fields
        # on old records read as solo (the pre-fusion behavior), so
        # solo-vs-old keeps gating exactly as before.
        base_fused = bool(base_rec.get("fusion", False))
        rec_fused = bool(rec.get("fusion", False))
        if base_fused != rec_fused:
            fusion_mismatches += 1
            entry["fusion_mismatch"] = True
            entry["baseline_fusion"] = base_fused
            obs.inc("bench.compare_fusion_mismatch")
            obs.event("bench.compare_fusion_mismatch",
                      metric=rec["metric"], baseline_fusion=base_fused,
                      current_fusion=rec_fused)
            log(f"## compare: fusion-mode mismatch on {rec['metric']} "
                f"(baseline fusion={base_fused}, this run "
                f"fusion={rec_fused}) — not gated")
            rates.append(entry)
            continue
        if value < (1.0 - threshold) * base_val:
            entry["regressed"] = True
            regressed.append(rec["metric"])
        rates.append(entry)
    if run_report:
        base_rr, _ = led.baseline("run_report")
        base_spans = {}
        if base_rr is not None:
            base_spans = (((base_rr.get("payload") or {})
                           .get("run_report") or {}).get("spans") or {})
        for name, agg in sorted((run_report.get("spans") or {}).items()):
            b = base_spans.get(name)
            if not b or not b.get("total_s"):
                continue
            spans.append({"span": name,
                          "total_s": agg["total_s"],
                          "baseline_total_s": b["total_s"],
                          "ratio": round(agg["total_s"] / b["total_s"],
                                         3)})
    return {"fingerprint": led.fingerprint, "threshold": threshold,
            "rates": rates, "spans": spans,
            "skipped_degraded_baselines": skipped_degraded,
            "plan_mismatches": plan_mismatches,
            "kernel_backend_mismatches": backend_mismatches,
            "vector_accumulator_mismatches": accumulator_mismatches,
            "fusion_mismatches": fusion_mismatches,
            "sweep_config_batch_mismatches": sweep_batch_mismatches,
            "mesh_topology_mismatches": topology_mismatches,
            "kernel_backend": cur_backend,
            "plan": cur_plan,
            "regressed": regressed}


def compare_verdict_line(regressions):
    """The one-line ``--compare`` verdict printed to STDOUT (before the
    headline JSON, which stays the last stdout line): interactive runs
    see the gate result without opening the artifact."""
    if regressions["regressed"]:
        return (f"COMPARE: REGRESSED — "
                f"{', '.join(regressions['regressed'])} dropped "
                f">{regressions['threshold']:.0%} vs last-known-good "
                f"(fingerprint {regressions['fingerprint']})")
    if regressions.get("plan_mismatches"):
        plan = regressions.get("plan") or {}
        return (f"COMPARE: plan mismatch — "
                f"{regressions['plan_mismatches']} rate(s) not gated: "
                f"this run ran {plan.get('plan_source', 'default')} "
                f"knobs (plan {plan.get('plan_hash')}) against a "
                "baseline from a different knob plan; re-baseline "
                "with matching plans before gating")
    if regressions.get("kernel_backend_mismatches"):
        return (f"COMPARE: kernel-backend mismatch — "
                f"{regressions['kernel_backend_mismatches']} rate(s) "
                f"not gated: this run ran kernel_backend="
                f"{regressions.get('kernel_backend')} against a "
                "baseline from the other backend; re-baseline with "
                "matching backends before gating")
    if regressions.get("vector_accumulator_mismatches"):
        return (f"COMPARE: vector-accumulator mismatch — "
                f"{regressions['vector_accumulator_mismatches']} "
                "rate(s) not gated: this run's vector records ran the "
                "other accumulator (fx vs f32) than their baseline; "
                "re-baseline with matching accumulators before gating")
    if regressions.get("fusion_mismatches"):
        return (f"COMPARE: fusion-mode mismatch — "
                f"{regressions['fusion_mismatches']} rate(s) not "
                "gated: this run's serve records ran the other "
                "fusion mode than their baseline; re-baseline with "
                "matching modes before gating")
    if regressions.get("sweep_config_batch_mismatches"):
        return (f"COMPARE: sweep-config-batch mismatch — "
                f"{regressions['sweep_config_batch_mismatches']} "
                "rate(s) not gated: this run's megasweep records ran "
                "a different config-batch width than their baseline "
                "(a different dispatch regime of the same "
                "bit-identical kernel); re-baseline with matching "
                "widths before gating")
    if regressions.get("mesh_topology_mismatches"):
        return (f"COMPARE: mesh-topology mismatch — "
                f"{regressions['mesh_topology_mismatches']} rate(s) "
                "not gated: this run ran a different mesh_topology "
                "(flat vs hier — a different collective schedule of "
                "the same bit-identical release) than its baseline; "
                "re-baseline with matching topologies before gating")
    n_based = sum(1 for r in regressions["rates"]
                  if r.get("baseline") is not None and
                  not r.get("plan_mismatch") and
                  not r.get("kernel_backend_mismatch") and
                  not r.get("fusion_mismatch") and
                  not r.get("sweep_config_batch_mismatch") and
                  not r.get("mesh_topology_mismatch"))
    if n_based == 0:
        # Nothing was actually gated — say so, instead of an "on pace"
        # that reads as a passing verdict on a first run or a fresh
        # fingerprint with no last-known-good.
        return (f"COMPARE: no baseline — none of "
                f"{len(regressions['rates'])} rate(s) had a "
                f"last-known-good for fingerprint "
                f"{regressions['fingerprint']} (first run?)")
    return (f"COMPARE: on pace — {n_based} rate(s) within "
            f"{regressions['threshold']:.0%} of last-known-good "
            f"(fingerprint {regressions['fingerprint']})")


def _ensure_device_or_degrade():
    """Probe the accelerator with bounded retry + exponential backoff
    (jax backend initialization can block indefinitely on a wedged TPU
    tunnel — the r05 failure mode). Instead of aborting rc=3, exhausted
    retries fall back to a ``JAX_PLATFORMS=cpu`` run whose results are
    flagged ``"degraded": true`` — a parseable (if slow) benchmark beats
    a dead one. Returns the ``HealthReport``."""
    import os

    from pipelinedp_tpu.resilience import RetryPolicy, health

    policy = RetryPolicy(
        max_attempts=int(os.environ.get(
            "PIPELINEDP_TPU_PROBE_ATTEMPTS", "3")),
        base_delay_s=float(os.environ.get(
            "PIPELINEDP_TPU_PROBE_BACKOFF", "5.0")),
        multiplier=2.0, max_delay_s=60.0, jitter=0.1, seed=0)
    report = health.ensure_device_or_degrade(policy=policy)
    if report.degraded:
        log(f"## DEVICE UNREACHABLE after {report.attempts} probe "
            f"attempts (backoff {[round(b, 1) for b in report.backoff_s]}"
            f"s): {report.detail}")
        log("## falling back to JAX_PLATFORMS=cpu — results are flagged "
            '"degraded": true (wedged TPU tunnel?); rerun when the '
            "device is available for real numbers")
    return report


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes for a quick correctness pass")
    parser.add_argument("--rows", type=int, default=None)
    parser.add_argument("--flagship-only", action="store_true")
    parser.add_argument(
        "--stream-rows", type=int, default=None,
        help="streaming-ingest benchmark row count (default: 150M full "
        "runs / 200k smoke; 0 disables)")
    parser.add_argument(
        "--autotune", action="store_true",
        help="run the bounded execution-planner knob sweep on the "
        "streamed-percentile workload, append every trial to the run "
        "ledger, fit the cost model and write the plan file a "
        "subsequent plain run loads (pipelinedp_tpu/plan)")
    parser.add_argument(
        "--compare", action="store_true",
        help="diff this run's rates and span totals against the run "
        "ledger's last-known-good for the same environment fingerprint "
        "and emit a 'regressions' section in the artifact")
    parser.add_argument(
        "--strict", action="store_true",
        help="with --compare: exit nonzero when any rate dropped more "
        "than 10%% vs its last-known-good baseline")
    args = parser.parse_args()
    if args.stream_rows is None:
        args.stream_rows = 200_000 if args.smoke else 150_000_000

    # Live telemetry (opt-in via PIPELINEDP_TPU_HEARTBEAT), armed
    # BEFORE the device probe: the probe is the stack's most notorious
    # staller (r4/r5 sat silently through a 300s timeout), so the
    # bench's stall action cancels a wedged probe at the stall deadline
    # — degradation with a flight record in seconds, not minutes.
    from pipelinedp_tpu.obs import monitor as obs_monitor
    from pipelinedp_tpu.resilience import health as health_mod
    monitor = obs_monitor.maybe_start(
        run_name=f"bench-{os.getpid()}",
        on_stall=lambda info: health_mod.cancel_active_probe())
    if monitor is not None:
        log(f"## heartbeat: {monitor.heartbeat_path} (every "
            f"{monitor.interval_s:g}s; stall deadline "
            f"{monitor.stall_s:g}s; flight record on stall: "
            f"{monitor.flight_path})")

    health_report = _ensure_device_or_degrade()

    # Persistent XLA compile cache (opt-in): re-runs skip the cold
    # compilation of every fused kernel shape.
    from pipelinedp_tpu.ingest import maybe_enable_compile_cache
    cache_dir = maybe_enable_compile_cache()
    if cache_dir:
        log(f"## persistent compile cache: {cache_dir}")

    # The execution planner's plan file: like the run ledger, the
    # bench falls back to a cwd-local directory when neither
    # PIPELINEDP_TPU_PLAN_DIR nor the compile cache names one — so
    # `bench.py --autotune` followed by a plain `bench.py` in the same
    # directory closes the loop without any env setup.
    from pipelinedp_tpu import plan as plan_mod
    plan_mod.set_default_dir(os.path.join(os.getcwd(), ".pdp_plan"))
    # Snapshot the plan provenance NOW — before any record injects its
    # measurement scaffolding (chunk env, cap seams) — so every record
    # and the headline carry the regime the run was launched under.
    plan_provenance()

    if args.autotune:
        rc = run_autotune(args)
        if monitor is not None:
            obs_monitor.stop()
        sys.exit(rc)

    import pipelinedp_tpu as pdp

    if monitor is not None:
        # The pace baseline keys on the environment fingerprint, which
        # probes jax.devices() — only safe to compute AFTER the health
        # probe settled the platform (a wedged runtime blocks there).
        from pipelinedp_tpu.obs import store as obs_store
        monitor.attach_baseline(obs_store.fingerprint_key(
            env_fingerprint()))

    if args.smoke:
        n_rows, n_users, local_rows = 50_000, 5_000, 20_000
        q_rows, q_parts = 100_000, 2_000
        a_rows, a_configs = 20_000, 8
    else:
        n_rows = args.rows or 5_000_000
        n_users, local_rows = 200_000, 250_000
        q_rows, q_parts = 10_000_000, 100_000
        # vs_baseline is a unit rate (config*rows/s), comparable across
        # sizes; the host baseline is measured on a small slice.
        # BASELINE config 5 specifies a 10,000-configuration sweep.
        a_rows, a_configs = 500_000, 10_000

    def flagship_params():
        return pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4, max_contributions_per_partition=2,
            min_value=0.0, max_value=10.0)

    # Flagship (BASELINE config 2 shape): COUNT+SUM+MEAN over 60k parts.
    ds_60k = zipf_dataset(n_rows, n_users, 2_000 if args.smoke else 60_000)
    flagship = bench_config("dp_count_sum_mean_rows_per_sec",
                            flagship_params(), ds_60k, local_rows)
    roofline_probe(ds_60k)

    if not args.flagship_only:
        # Config 1: COUNT over ~1k partitions.
        ds_1k = zipf_dataset(n_rows, n_users, 1_000, seed=2)
        bench_config(
            "dp_count_1k_partitions_rows_per_sec",
            pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT],
                noise_kind=pdp.NoiseKind.LAPLACE,
                max_partitions_contributed=4,
                max_contributions_per_partition=2),
            ds_1k, local_rows)

        # Config 2 (Gaussian variant): SUM+MEAN over 60k partitions.
        bench_config(
            "dp_sum_mean_gaussian_rows_per_sec",
            pdp.AggregateParams(
                metrics=[pdp.Metrics.SUM, pdp.Metrics.MEAN],
                noise_kind=pdp.NoiseKind.GAUSSIAN,
                max_partitions_contributed=4,
                max_contributions_per_partition=2,
                min_value=0.0, max_value=10.0),
            ds_60k, local_rows)

        # Config 3: PRIVACY_ID_COUNT with Laplace thresholding
        # (restaurant_visits shape: each user visits few venues).
        ds_rest = zipf_dataset(n_rows, max(n_users, n_rows // 16),
                               3_000 if not args.smoke else 300, seed=3)
        bench_config(
            "dp_privacy_id_count_thresholding_rows_per_sec",
            pdp.AggregateParams(
                metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                noise_kind=pdp.NoiseKind.LAPLACE,
                max_partitions_contributed=4,
                max_contributions_per_partition=1,
                partition_selection_strategy=(
                    pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING)),
            ds_rest, local_rows)

        # Config 4: quantiles + variance over 10M rows / 100k partitions.
        ds_q = zipf_dataset(q_rows, n_users, q_parts, seed=4)
        bench_config(
            "dp_quantile_variance_rows_per_sec",
            pdp.AggregateParams(
                metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90),
                         pdp.Metrics.PERCENTILE(99), pdp.Metrics.VARIANCE],
                noise_kind=pdp.NoiseKind.LAPLACE,
                max_partitions_contributed=4,
                max_contributions_per_partition=2,
                min_value=0.0, max_value=10.0),
            ds_q, min(local_rows, 50_000), repeats=3)  # 10M rows: 3 is enough

        # Per-phase walk breakdown at (at least) a 2^16-partition
        # synthetic — the driver-measurable evidence for walk-phase
        # claims (t_noise / t_hist / t_walk / t_total).
        walk_breakdown_probe(max(1 << 16, q_parts),
                             min(q_rows, 4_000_000))

        # Streamed two-pass percentiles + the pass-B multi-tile sweep
        # record (shrunken cap seam, so CPU runs witness the
        # round-count collapse too).
        bench_streamed_percentile(60_000 if args.smoke else 2_000_000)

        # The kernel-backend A/B: both hot-path workloads, warm, same
        # data, xla vs pallas, with per-phase roofline verdicts and a
        # bit-parity cross-check in one record.
        bench_kernel_backend_compare(30_000 if args.smoke else 500_000,
                                     smoke=args.smoke)

        # The mesh-topology A/B: flat vs hier on the 8-device mesh
        # with two simulated hosts, same data, bit-parity
        # cross-checked, dcn/ici byte counters embedded.
        bench_mesh_topology_compare(30_000 if args.smoke else 500_000,
                                    smoke=args.smoke)

        # Wide-D vector aggregation: VECTOR_SUM at D in {64,256,1024}
        # streamed through the ingest ring under the fx accumulator,
        # with the Pallas wide-D segment sum requested and the
        # dispatch-or-fallback evidence on the record.
        bench_dp_vector_sum(30_000 if args.smoke else 2_000_000,
                            smoke=args.smoke)

        # The resident-service record: cold vs warm request latency +
        # requests/s through a warm multi-tenant serve.Service.
        bench_serve_latency(30_000 if args.smoke else 500_000,
                            smoke=args.smoke)

        # Request fusion A/B at the acceptance shape (8 concurrent
        # 20k-row same-signature requests): solo vs fused in one
        # process, same-seed bit-parity cross-checked.
        bench_serve_fused_throughput(20_000, smoke=args.smoke)

        # Observability-cost A/B: the same serve burst with the full
        # trace-context + metrics + live-/metrics-scrape plane armed
        # vs fully dark, same-seed bit-parity cross-checked; gates
        # the instrumented path's throughput under --compare.
        bench_obs_overhead(5_000 if args.smoke else 20_000,
                           smoke=args.smoke)

        # DP heavy hitters over an unbounded string key space: the
        # sketch-first two-phase path at ~1e7 rows over ~1e6 distinct
        # power-law keys (smoke: 200k over 20k).
        bench_dp_heavy_hitters(200_000 if args.smoke else 10_000_000,
                               smoke=args.smoke)

        # Config 5: the analysis epsilon-sweep.
        bench_analysis_sweep(a_rows, max(1000, a_rows // 25),
                             1_000 if not args.smoke else 100, a_configs)

        # The config-axis megasweep: walked-vs-batched A/B at K in
        # {16,64,256} over a >=1e6-row synthetic, per-config
        # bit-parity cross-checked, dispatch counts witnessed from the
        # cost observatory.
        bench_utility_megasweep(20_000 if args.smoke else 1_000_000,
                                smoke=args.smoke)

        # The north-star workload at ITS OWN scale: MovieLens-25M is
        # 25M ratings x 162k users x 59k movies (BASELINE configs 1-2).
        # The flagship above runs a matched SHAPE at 5M rows; this
        # record runs COUNT+SUM+MEAN at exactly 25M rows through the
        # standard (non-smoke, single-batch) path so the stated
        # workload size itself is driver-witnessed.
        if not args.smoke:
            ds_25m = zipf_dataset(25_000_000, 162_000, 59_000, seed=6)
            bench_config("dp_count_sum_mean_25m_rows_per_sec",
                         flagship_params(), ds_25m, local_rows,
                         repeats=3)
            del ds_25m

        # Streaming ingest past the 2^27-row single-batch cap.
        if args.stream_rows:
            bench_streaming(args.stream_rows)

    # The tunneled link has multi-minute slow windows (measured 4x+
    # swings); if the flagship's whole best-of-5 landed in one, a
    # second time-separated sample at the end of the run corrects the
    # headline. Keep whichever sample is better — both logged. Runs in
    # EVERY mode (--flagship-only exists to produce just the headline,
    # which needs the guard most).
    log("## flagship re-sample (slow-window guard)")
    flagship2 = bench_config(
        "dp_count_sum_mean_rows_per_sec", flagship_params(), ds_60k,
        local_rows, repeats=3,
        local_baseline=flagship["_local_baseline"])
    if flagship2["value"] > flagship["value"]:
        flagship = flagship2

    # The driver's contract: exactly one JSON line on stdout. A degraded
    # (CPU-fallback) run says so — its numbers measure the fallback, not
    # the accelerator. The env fingerprint rides on every record; with
    # PIPELINEDP_TPU_TRACE set the headline additionally carries the
    # schema-versioned run report (spans + counters/events + the privacy
    # audit section) and a Chrome-trace file lands next to it for
    # Perfetto. Every run — traced or not — appends its report to the
    # durable run-ledger store as the "run_report" entry.
    from pipelinedp_tpu import obs
    headline = {k: flagship[k] for k in
                ("metric", "value", "unit", "vs_baseline",
                 "host_s", "device_s", "kernel_backend")
                if k in flagship}
    headline["degraded"] = bool(health_report.degraded)
    # Plan provenance on the artifact of record: which knob plan
    # produced this rate (autotuned / env-override / default + the
    # plan-file hash) — the TPU re-capture's "which plan" evidence.
    headline.update(plan_provenance())
    if health_report.degraded:
        # The artifact used to say only "degraded": true (plus an
        # attempt count buried in stderr) — now it carries the probe
        # diagnosis and, when the stall watchdog fired, the stall
        # diagnosis + flight-record path, so a wedged capture explains
        # itself without session notes.
        diagnosis = {"probe_attempts": health_report.attempts,
                     "detail": health_report.detail}
        if monitor is not None and monitor.stalls:
            last = monitor.stalls[-1]
            diagnosis["stall"] = last["diagnosis"]
            diagnosis["flight_record"] = last["flight_record"]
        headline["degraded_diagnosis"] = diagnosis
    headline["env"] = env_fingerprint()
    # ONE ledger snapshot feeds every exporter, so the trace file, the
    # report and the stored ledger entry agree span-for-span; the
    # cached fingerprint skips a second device/git probe.
    snap = obs.ledger().snapshot()
    report = record_run_report(snapshot=snap)
    if obs.trace_enabled():
        trace_path = obs.write_chrome_trace(snapshot=snap)
        with open(trace_path + ".report.json", "w",
                  encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        headline["run_report"] = report
        log(f"## chrome trace: {trace_path} (open at "
            f"https://ui.perfetto.dev); run report: "
            f"{trace_path}.report.json")
    regressions = None
    if args.compare:
        regressions = compare_to_baseline(run_report=report)
        headline["regressions"] = regressions
        if regressions["regressed"]:
            log(f"## REGRESSIONS: rates dropped "
                f">{regressions['threshold']:.0%} vs last-known-good: "
                f"{regressions['regressed']}")
        else:
            log("## compare: no rate regressions vs last-known-good "
                f"(fingerprint {regressions['fingerprint']})")
        print(compare_verdict_line(regressions))
    print(json.dumps(headline))
    if monitor is not None:
        obs_monitor.stop()  # writes one final heartbeat beat, joins
    if args.strict and regressions and regressions["regressed"]:
        # Mark this run as gate-failed so its regressed numbers never
        # become the next run's baseline (the gate must stay red until
        # the regression is actually fixed, not self-clear).
        _bench_ledger().append("bench.gate_failed",
                               {"regressed": regressions["regressed"]})
        sys.exit(1)


if __name__ == "__main__":
    main()
